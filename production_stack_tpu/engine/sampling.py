"""Token sampling: greedy / temperature / top-k / top-p, fully vectorized.

One jitted function handles a mixed batch (each sequence has its own
temperature/top-k/top-p/seed); the greedy-vs-sampled choice is a
``jnp.where``, not control flow, so the whole batch stays one XLA program.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def _apply_top_k(logits: jax.Array, top_k: jax.Array) -> jax.Array:
    """Mask logits below the k-th largest.  top_k<=0 disables."""
    V = logits.shape[-1]
    sorted_desc = jnp.sort(logits, axis=-1)[..., ::-1]  # [S, V]
    k = jnp.clip(top_k, 1, V)
    kth = jnp.take_along_axis(sorted_desc, (k - 1)[:, None], axis=-1)  # [S,1]
    masked = jnp.where(logits < kth, NEG_INF, logits)
    return jnp.where((top_k > 0)[:, None], masked, logits)


def _apply_top_p(logits: jax.Array, top_p: jax.Array) -> jax.Array:
    """Nucleus filtering.  top_p>=1 disables."""
    sorted_desc = jnp.sort(logits, axis=-1)[..., ::-1]
    probs = jax.nn.softmax(sorted_desc, axis=-1)
    cumulative = jnp.cumsum(probs, axis=-1)
    # Keep tokens whose cumulative mass (exclusive) is below top_p; the
    # first token is always kept.
    keep = (cumulative - probs) < top_p[:, None]
    # Smallest kept logit is the threshold.
    threshold = jnp.min(
        jnp.where(keep, sorted_desc, jnp.inf), axis=-1, keepdims=True
    )
    masked = jnp.where(logits < threshold, NEG_INF, logits)
    return jnp.where((top_p < 1.0)[:, None], masked, logits)


def _apply_min_p(logits: jax.Array, min_p: jax.Array) -> jax.Array:
    """vLLM min_p: drop tokens whose probability is below
    ``min_p * max_prob``.  min_p<=0 disables."""
    probs = jax.nn.softmax(logits, axis=-1)
    cut = jnp.max(probs, axis=-1, keepdims=True) * min_p[:, None]
    masked = jnp.where(probs < cut, NEG_INF, logits)
    return jnp.where((min_p > 0)[:, None], masked, logits)


def sample_tokens(
    logits: jax.Array,  # [S, V] fp32
    temperature: jax.Array,  # [S]
    top_p: jax.Array,  # [S]
    top_k: jax.Array,  # [S] int32
    step_key: jax.Array,  # PRNG key
    seq_seeds: jax.Array,  # [S] int32 per-sequence seed fold
    min_p: Optional[jax.Array] = None,  # [S]; None -> disabled
) -> jax.Array:
    """Returns sampled token ids [S] (int32)."""
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    safe_temp = jnp.where(temperature > 0, temperature, 1.0)
    scaled = logits / safe_temp[:, None]
    scaled = _apply_top_k(scaled, top_k)
    scaled = _apply_top_p(scaled, top_p)
    if min_p is not None:
        scaled = _apply_min_p(scaled, min_p)

    keys = jax.vmap(lambda s: jax.random.fold_in(step_key, s))(seq_seeds)
    sampled = jax.vmap(
        lambda key, row: jax.random.categorical(key, row)
    )(keys, scaled).astype(jnp.int32)

    return jnp.where(temperature > 0, sampled, greedy)


def compute_logprobs(logits: jax.Array, token_ids: jax.Array) -> jax.Array:
    """Log-prob of the chosen tokens: [S, V], [S] -> [S]."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    return jnp.take_along_axis(logp, token_ids[:, None], axis=-1)[:, 0]


def occurrence_state(
    out_tokens: jax.Array,  # [S, L] int32 generated-so-far, -1 padded
    ctx_tokens: jax.Array,  # [S, Lc] int32 prompt+generated, -1 padded
    vocab_size: int,
):
    """Device-resident per-sequence token-occurrence state: the
    ``counts`` histogram over GENERATED tokens (int16 — the bounded
    per-token occurrence count feeding presence/frequency) and the
    ``seen`` bitmap over prompt AND generated tokens (repetition).
    Built by scatter from the small [S, L] id arrays; the K-step decode
    window carries both through its scan and updates them per sampled
    token, so penalties apply on-device with no host round-trip."""
    valid = out_tokens >= 0
    ids = jnp.where(valid, out_tokens, 0)
    counts = jax.vmap(
        lambda i, v: jnp.zeros((vocab_size,), jnp.int16).at[i].add(
            v.astype(jnp.int16)
        )
    )(ids, valid)
    cvalid = ctx_tokens >= 0
    cids = jnp.where(cvalid, ctx_tokens, 0)
    seen = jax.vmap(
        lambda i, v: jnp.zeros((vocab_size,), jnp.bool_).at[i].max(v)
    )(cids, cvalid)
    return counts, seen


def apply_penalties_state(
    logits: jax.Array,  # [S, V] fp32
    counts: jax.Array,  # [S, V] int16 generated-token occurrence counts
    seen: jax.Array,  # [S, V] bool prompt+generated occurrence bitmap
    presence: jax.Array,  # [S]
    frequency: jax.Array,  # [S]
    repetition: jax.Array,  # [S]; 1.0 = off
) -> jax.Array:
    """The ONE place the penalty math lives (host single-step path and
    the K-step decode window both land here, so the two can never
    diverge).  HF/vLLM ``repetition_penalty`` over prompt AND generated
    tokens applies to the RAW logits first (for every seen token,
    positive logits divide by the penalty, negative multiply — HF
    ``RepetitionPenaltyLogitsProcessor``), then the OpenAI
    presence/frequency penalties over the GENERATED tokens (vLLM
    semantics: the prompt is not penalized).  Per sequence:
    ``logit[t] -= presence*[count(t)>0] + frequency*count(t)``.

    Order matters when both families hit the same token (HF/vLLM apply
    repetition before the subtraction: logit 2.0, presence 1.5, rep 2.0
    must give -0.5, not +0.25).  With penalties off the result is
    bit-identical to the input (x/1.0, x*1.0 and x-0.0 are exact)."""
    rep = repetition[:, None]
    scaled = jnp.where(logits > 0, logits / rep, logits * rep)
    logits = jnp.where(seen, scaled, logits)
    countsf = counts.astype(jnp.float32)
    penalty = presence[:, None] * (countsf > 0) + frequency[:, None] * countsf
    return logits - penalty


def apply_penalties(
    logits: jax.Array,  # [S, V] fp32
    out_tokens: jax.Array,  # [S, L] int32 generated-so-far, -1 padded
    presence: jax.Array,  # [S]
    frequency: jax.Array,  # [S]
    repetition: jax.Array = None,  # [S]; 1.0 = off
    ctx_tokens: jax.Array = None,  # [S, Lc] prompt+generated, -1 padded
) -> jax.Array:
    """Single-step host-path entry: build the occurrence state from the
    per-step token-id arrays, then apply the shared penalty math.
    ``repetition=None`` skips the seen-bitmap build entirely (the
    common presence/frequency-only batch)."""
    S, V = logits.shape
    if repetition is not None:
        counts, seen = occurrence_state(
            out_tokens,
            ctx_tokens if ctx_tokens is not None else out_tokens,
            V,
        )
        return apply_penalties_state(
            logits, counts, seen, presence, frequency, repetition
        )
    valid = out_tokens >= 0
    ids = jnp.where(valid, out_tokens, 0)
    counts = jax.vmap(
        lambda i, v: jnp.zeros((V,), jnp.float32).at[i].add(
            v.astype(jnp.float32)
        )
    )(ids, valid)
    penalty = presence[:, None] * (counts > 0) + frequency[:, None] * counts
    return logits - penalty


def top_logprobs_of(
    logits: jax.Array,  # [S, V] fp32
    token_ids: jax.Array,  # [S] chosen tokens
    k: int,
):
    """Chosen-token logprob + top-k alternatives (OpenAI ``logprobs``).
    Returns (chosen [S], top_ids [S, k], top_logps [S, k])."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    chosen = jnp.take_along_axis(logp, token_ids[:, None], axis=-1)[:, 0]
    top_logps, top_ids = jax.lax.top_k(logp, k)
    return chosen, top_ids.astype(jnp.int32), top_logps
