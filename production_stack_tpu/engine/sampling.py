"""Token sampling: greedy / temperature / top-k / top-p, fully vectorized.

One jitted function handles a mixed batch (each sequence has its own
temperature/top-k/top-p/seed); the greedy-vs-sampled choice is a
``jnp.where``, not control flow, so the whole batch stays one XLA program.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def _apply_top_k(logits: jax.Array, top_k: jax.Array) -> jax.Array:
    """Mask logits below the k-th largest.  top_k<=0 disables."""
    V = logits.shape[-1]
    sorted_desc = jnp.sort(logits, axis=-1)[..., ::-1]  # [S, V]
    k = jnp.clip(top_k, 1, V)
    kth = jnp.take_along_axis(sorted_desc, (k - 1)[:, None], axis=-1)  # [S,1]
    masked = jnp.where(logits < kth, NEG_INF, logits)
    return jnp.where((top_k > 0)[:, None], masked, logits)


def _apply_top_p(logits: jax.Array, top_p: jax.Array) -> jax.Array:
    """Nucleus filtering.  top_p>=1 disables."""
    sorted_desc = jnp.sort(logits, axis=-1)[..., ::-1]
    probs = jax.nn.softmax(sorted_desc, axis=-1)
    cumulative = jnp.cumsum(probs, axis=-1)
    # Keep tokens whose cumulative mass (exclusive) is below top_p; the
    # first token is always kept.
    keep = (cumulative - probs) < top_p[:, None]
    # Smallest kept logit is the threshold.
    threshold = jnp.min(
        jnp.where(keep, sorted_desc, jnp.inf), axis=-1, keepdims=True
    )
    masked = jnp.where(logits < threshold, NEG_INF, logits)
    return jnp.where((top_p < 1.0)[:, None], masked, logits)


def sample_tokens(
    logits: jax.Array,  # [S, V] fp32
    temperature: jax.Array,  # [S]
    top_p: jax.Array,  # [S]
    top_k: jax.Array,  # [S] int32
    step_key: jax.Array,  # PRNG key
    seq_seeds: jax.Array,  # [S] int32 per-sequence seed fold
) -> jax.Array:
    """Returns sampled token ids [S] (int32)."""
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    safe_temp = jnp.where(temperature > 0, temperature, 1.0)
    scaled = logits / safe_temp[:, None]
    scaled = _apply_top_k(scaled, top_k)
    scaled = _apply_top_p(scaled, top_p)

    keys = jax.vmap(lambda s: jax.random.fold_in(step_key, s))(seq_seeds)
    sampled = jax.vmap(
        lambda key, row: jax.random.categorical(key, row)
    )(keys, scaled).astype(jnp.int32)

    return jnp.where(temperature > 0, sampled, greedy)


def compute_logprobs(logits: jax.Array, token_ids: jax.Array) -> jax.Array:
    """Log-prob of the chosen tokens: [S, V], [S] -> [S]."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    return jnp.take_along_axis(logp, token_ids[:, None], axis=-1)[:, 0]
