"""TPU-native production inference stack.

A Kubernetes-native control plane and TPU serving data plane with the
capabilities of vLLM Production Stack (reference: /root/reference):

- OpenAI-compatible L7 request router with pluggable routing logic
  (round-robin, session affinity via consistent hashing, KV-aware).
- Kubernetes service discovery, dynamic hot-reconfiguration, and a
  native operator.
- A JAX/XLA/Pallas serving engine (the reference delegates compute to
  external vLLM CUDA images; on TPU the stack is standalone).
- KV-cache offload: TPU HBM -> host DRAM -> remote shared store.
- Prometheus/Grafana observability keyed on TPU engine metrics.

Reference layer map: see SURVEY.md section 1.
"""

from production_stack_tpu.version import __version__

__all__ = ["__version__"]
