"""Self-contained observability layer: request tracing + latency histograms.

No OpenTelemetry / client-library dependency.  ``trace`` carries W3C
traceparent propagation and bounded per-request timelines; ``histogram``
the shared Prometheus-style bucket layout; ``engine`` the engine-side hub
(EngineObs) both the real engine core and the fake CI engine feed.
"""

from production_stack_tpu.obs.histogram import (  # noqa: F401
    DEFAULT_LATENCY_BUCKETS,
    Histogram,
    render_histogram,
    render_labeled_histograms,
)
from production_stack_tpu.obs.trace import (  # noqa: F401
    RequestTrace,
    Span,
    Tracer,
    make_traceparent,
    new_trace_id,
    parse_traceparent,
)
from production_stack_tpu.obs.compile_tracker import (  # noqa: F401
    CompileTracker,
)
from production_stack_tpu.obs.flight_recorder import (  # noqa: F401
    FlightRecorder,
    WindowRecord,
    WINDOW_KINDS,
)
from production_stack_tpu.obs.engine import (  # noqa: F401
    EngineObs,
    PHASE_SPAN_NAMES,
    REQUEST_HISTS,
    STEP_PHASES,
)
