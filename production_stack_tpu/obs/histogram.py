"""Dependency-free Prometheus-style latency histograms.

Why not ``prometheus_client.Histogram``: the engine server renders its own
exposition text (vocabulary.render_prometheus) rather than owning a global
registry, the router needs per-server quantile *reads* for the periodic log
dump (the client library hides bucket state behind collect()), and both
sides must share one bucket layout so router-side and engine-side p99s are
comparable.  This module is that shared layout: thread-safe observe(), a
bucket-interpolated quantile estimator, and Prometheus text rendering that
concatenates cleanly after any existing exposition body.
"""

from __future__ import annotations

import threading
from typing import Dict, Iterable, List, Tuple

# Shared latency bucket layout (seconds): spans sub-ms step phases up to
# minute-long streamed requests.  One layout everywhere keeps
# histogram_quantile() comparable across the router and engine families.
DEFAULT_LATENCY_BUCKETS: Tuple[float, ...] = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)


def _fmt(v: float) -> str:
    """Prometheus-friendly float formatting (no trailing zeros noise)."""
    return repr(float(v))


class Histogram:
    """Cumulative histogram: fixed upper bounds + one +Inf bucket."""

    __slots__ = ("bounds", "counts", "sum", "count", "_lock")

    def __init__(self, bounds: Iterable[float] = DEFAULT_LATENCY_BUCKETS):
        self.bounds: Tuple[float, ...] = tuple(float(b) for b in bounds)
        assert list(self.bounds) == sorted(self.bounds), "bounds must ascend"
        self.counts: List[int] = [0] * (len(self.bounds) + 1)  # last = +Inf
        self.sum: float = 0.0
        self.count: int = 0
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        value = float(value)
        idx = len(self.bounds)
        for i, bound in enumerate(self.bounds):
            if value <= bound:
                idx = i
                break
        with self._lock:
            self.counts[idx] += 1
            self.sum += value
            self.count += 1

    def quantile(self, q: float) -> float:
        """Bucket-interpolated quantile estimate (what PromQL's
        histogram_quantile computes); 0.0 when empty."""
        with self._lock:
            total = self.count
            counts = list(self.counts)
        if total == 0:
            return 0.0
        rank = q * total
        cumulative = 0
        for i, c in enumerate(counts):
            prev_cum = cumulative
            cumulative += c
            if cumulative >= rank:
                if i >= len(self.bounds):
                    # +Inf bucket: the last finite bound is the best claim.
                    return self.bounds[-1] if self.bounds else 0.0
                lo = self.bounds[i - 1] if i > 0 else 0.0
                hi = self.bounds[i]
                if c == 0:
                    return hi
                return lo + (hi - lo) * (rank - prev_cum) / c
        return self.bounds[-1] if self.bounds else 0.0

    def render_lines(self, name: str, label_str: str = "") -> List[str]:
        """Prometheus text lines for this histogram (no # TYPE header —
        family headers are the caller's job so labeled instances share one)."""
        with self._lock:
            counts = list(self.counts)
            total_sum, total_count = self.sum, self.count
        lines = []
        sep = "," if label_str else ""
        cumulative = 0
        for bound, c in zip(self.bounds, counts):
            cumulative += c
            lines.append(
                f'{name}_bucket{{{label_str}{sep}le="{_fmt(bound)}"}} {cumulative}'
            )
        cumulative += counts[-1]
        lines.append(f'{name}_bucket{{{label_str}{sep}le="+Inf"}} {cumulative}')
        if label_str:
            lines.append(f"{name}_sum{{{label_str}}} {_fmt(total_sum)}")
            lines.append(f"{name}_count{{{label_str}}} {total_count}")
        else:
            lines.append(f"{name}_sum {_fmt(total_sum)}")
            lines.append(f"{name}_count {total_count}")
        return lines


def render_histogram(name: str, hist: Histogram, help_text: str = "") -> str:
    lines = []
    if help_text:
        lines.append(f"# HELP {name} {help_text}")
    lines.append(f"# TYPE {name} histogram")
    lines.extend(hist.render_lines(name))
    return "\n".join(lines) + "\n"


def render_labeled_histograms(
    name: str,
    by_label: Dict[str, Histogram],
    label: str = "server",
    help_text: str = "",
) -> str:
    """One histogram family with one instance per label value."""
    lines = []
    if help_text:
        lines.append(f"# HELP {name} {help_text}")
    lines.append(f"# TYPE {name} histogram")
    for value in sorted(by_label):
        escaped = value.replace("\\", "\\\\").replace('"', '\\"')
        lines.extend(
            by_label[value].render_lines(name, f'{label}="{escaped}"')
        )
    return "\n".join(lines) + "\n"


