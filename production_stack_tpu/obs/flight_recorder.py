"""Window flight recorder: a bounded ring of per-dispatch records that
makes the device-resident scan engine explainable.

The K-step window engine (PR 8/11/15/16) packs multiple prompts' chunks,
speculative drafts and overlapped transfers into single opaque dispatches;
per-request spans alone cannot say *which window* a slow token rode or what
else shared it.  The recorder stamps one ``WindowRecord`` per dispatch
(plan composition, chain depth, planner fallback, inherited host gap) and
completes it at collect (tokens emitted/delivered/wasted, drafted/accepted,
chunk-token delivery, attributed wall time), serving the ring at
``GET /debug/windows`` and joining a request's records into
``/debug/requests/{id}``.

Lock discipline matches the tracer: records are created and completed on
the engine step thread; the HTTP server snapshots from the event loop, so
every ring mutation and every snapshot holds ``_lock``.  A dispatched-but-
uncollected record lives only on its ``_PendingStep`` (single-threaded
step-loop state) and enters the shared ring exactly once, at collect — so
"every dispatched window appears exactly once" holds by construction.

Attribution: collects are FIFO on the step thread, so
``attributed_s = collected_at - max(dispatched_at, previous collected_at)``
telescopes — summing a request's windows recovers its decode-phase wall
time even under the depth-2 lookahead pipeline, where raw
(collect - dispatch) intervals overlap and would double-count.

Disabled (``obs.tracing=False``) the recorder is never consulted: the
engine gates every call on ``obs.enabled`` and ``on_dispatch`` returns
None, so the fast path carries zero recorder state.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from typing import Deque, Dict, List, Optional, Tuple

# The closed set of dispatch kinds.  Single-step paths record too —
# without them the ring has holes and per-request attribution cannot sum
# to decode wall time.
#   prefill - a standalone prefill chunk (no decode rows)
#   decode  - a pure decode dispatch (K=1 single step or K-step window)
#   mixed   - decode + packed prefill chunks (K=1 fused step or K-step
#             mixed window)
#   spec    - fused speculative window (draft+verify in the scan)
WINDOW_KINDS = ("prefill", "decode", "mixed", "spec")


@dataclasses.dataclass
class WindowRecord:
    """One engine dispatch, stamped at launch and completed at collect."""

    window_id: int
    kind: str                      # one of WINDOW_KINDS
    k: int                         # planned scan iterations (1 = single step)
    rows: int                      # decode rows in the batch
    seq_ids: Tuple[str, ...]       # sequences riding this dispatch
    chain_depth: int = 0           # 0 = cold dispatch; n = nth chained window
    provisional: bool = False      # planned off in-flight carry (lookahead)
    spec_width: int = 0            # draft tokens per iteration (spec windows)
    drafter: str = ""              # proposal source ("ngram"/"model"), spec only
    chunk_prompts: int = 0         # distinct prompts whose chunks packed in
    chunk_tokens_planned: int = 0  # prompt tokens scheduled into the window
    chunk_tokens_delivered: int = 0
    fallback: Optional[str] = None  # planner decline reason, if it declined
    host_gap_s: float = 0.0        # host gap inherited from previous window
    transfer_overlap_s: float = 0.0  # H2D/D2H issued under in-flight window
    host_s: float = 0.0            # host-side dispatch cost
    dispatched_at: float = 0.0
    collected_at: Optional[float] = None
    attributed_s: float = 0.0      # non-overlapped wall time (telescoped)
    tokens_emitted: int = 0
    tokens_delivered: int = 0
    tokens_wasted: int = 0
    drafted: int = 0
    accepted: int = 0
    compile: bool = False          # an XLA compile fired inside this dispatch
    compile_s: float = 0.0

    def to_dict(self) -> Dict:
        d = {
            "window_id": self.window_id,
            "kind": self.kind,
            "k": self.k,
            "rows": self.rows,
            "seq_ids": list(self.seq_ids),
            "chain_depth": self.chain_depth,
            "provisional": self.provisional,
            "fallback": self.fallback,
            "host_gap_s": round(self.host_gap_s, 6),
            "host_s": round(self.host_s, 6),
            "dispatched_at": self.dispatched_at,
            "collected_at": self.collected_at,
            "attributed_s": round(self.attributed_s, 6),
            "tokens_emitted": self.tokens_emitted,
            "tokens_delivered": self.tokens_delivered,
            "tokens_wasted": self.tokens_wasted,
        }
        if self.spec_width:
            d["spec_width"] = self.spec_width
            d["drafter"] = self.drafter
            d["drafted"] = self.drafted
            d["accepted"] = self.accepted
        if self.chunk_prompts:
            d["chunk_prompts"] = self.chunk_prompts
            d["chunk_tokens_planned"] = self.chunk_tokens_planned
            d["chunk_tokens_delivered"] = self.chunk_tokens_delivered
        if self.transfer_overlap_s:
            d["transfer_overlap_s"] = round(self.transfer_overlap_s, 6)
        if self.compile:
            d["compile"] = True
            d["compile_s"] = round(self.compile_s, 6)
        return d


class FlightRecorder:
    """Bounded ring of completed ``WindowRecord``s, newest first.

    All mutation happens on the engine step thread; HTTP snapshot readers
    take ``_lock``.  Records between ``on_dispatch`` and ``on_collect``
    are owned exclusively by the step loop (via ``_PendingStep.rec``) and
    are not yet visible to readers.
    """

    def __init__(self, enabled: bool = True, ring_size: int = 512):
        self.enabled = bool(enabled)
        self.ring_size = max(1, int(ring_size))
        self._completed: Deque[WindowRecord] = deque(maxlen=self.ring_size)
        self._lock = threading.Lock()
        self._next_id = 0
        self._last_collected_at: Optional[float] = None
        self.dropped = 0          # records evicted from a full ring
        self.windows_recorded = 0  # completed records since boot

    # -- step-thread write path -------------------------------------------

    # stackcheck: allow=SC201 reason=flight-recorder timestamps are observability sinks; no plan state reads them (obs layer is plan-inert by contract)
    def on_dispatch(
        self,
        kind: str,
        *,
        k: int = 1,
        rows: int = 0,
        seq_ids: Tuple[str, ...] = (),
        chain_depth: int = 0,
        provisional: bool = False,
        spec_width: int = 0,
        drafter: str = "",
        chunk_prompts: int = 0,
        chunk_tokens_planned: int = 0,
        fallback: Optional[str] = None,
        host_gap_s: float = 0.0,
        transfer_overlap_s: float = 0.0,
        now: Optional[float] = None,
    ) -> Optional[WindowRecord]:
        """Stamp a new record at dispatch.  Returns None when disabled so
        gated call sites stay branch-cheap."""
        if not self.enabled:
            return None
        with self._lock:
            window_id = self._next_id
            self._next_id += 1
        return WindowRecord(
            window_id=window_id,
            kind=kind,
            k=int(k),
            rows=int(rows),
            seq_ids=tuple(seq_ids),
            chain_depth=int(chain_depth),
            provisional=bool(provisional),
            spec_width=int(spec_width),
            drafter=str(drafter),
            chunk_prompts=int(chunk_prompts),
            chunk_tokens_planned=int(chunk_tokens_planned),
            fallback=fallback,
            host_gap_s=float(host_gap_s),
            transfer_overlap_s=float(transfer_overlap_s),
            dispatched_at=now if now is not None else time.time(),
        )

    # stackcheck: allow=SC201 reason=flight-recorder timestamps are observability sinks; no plan state reads them (obs layer is plan-inert by contract)
    def on_collect(
        self,
        rec: Optional[WindowRecord],
        *,
        now: Optional[float] = None,
        host_s: float = 0.0,
        tokens_emitted: int = 0,
        tokens_delivered: int = 0,
        tokens_wasted: int = 0,
        chunk_tokens_delivered: int = 0,
        drafted: int = 0,
        accepted: int = 0,
    ) -> None:
        """Complete a record and publish it to the ring (exactly once per
        dispatched record — dropped lookahead steps complete here too,
        with their emissions counted as wasted)."""
        if rec is None:
            return
        now = now if now is not None else time.time()
        rec.collected_at = now
        rec.host_s = float(host_s)
        rec.tokens_emitted = int(tokens_emitted)
        rec.tokens_delivered = int(tokens_delivered)
        rec.tokens_wasted = int(tokens_wasted)
        rec.chunk_tokens_delivered = int(chunk_tokens_delivered)
        rec.drafted = int(drafted)
        rec.accepted = int(accepted)
        with self._lock:
            prev = self._last_collected_at
            floor = rec.dispatched_at if prev is None else max(
                rec.dispatched_at, prev)
            rec.attributed_s = max(0.0, now - floor)
            self._last_collected_at = now
            if len(self._completed) >= self.ring_size:
                self.dropped += 1
            self._completed.appendleft(rec)
            self.windows_recorded += 1

    def note_compile(self, rec: Optional[WindowRecord], seconds: float) -> None:
        """Mark a record compile-tainted (an XLA compile fired inside its
        dispatch/collect host work).  Called on the step thread before the
        record is published, so no lock is needed."""
        if rec is None:
            return
        rec.compile = True
        rec.compile_s += float(seconds)

    # -- HTTP snapshot read path ------------------------------------------

    def snapshot(
        self, seq: Optional[str] = None, limit: Optional[int] = None
    ) -> List[Dict]:
        """Lock-held dicts of completed records, newest first, optionally
        filtered to windows a sequence rode."""
        with self._lock:
            recs = [
                r.to_dict()
                for r in self._completed
                if seq is None or seq in r.seq_ids
            ]
        return recs if limit is None else recs[: max(0, int(limit))]

    def for_request(self, request_id: str) -> List[Dict]:
        """The windows one request rode, oldest first (timeline order) —
        the /debug/requests/{id} join payload."""
        recs = self.snapshot(seq=request_id)
        recs.reverse()
        return recs
