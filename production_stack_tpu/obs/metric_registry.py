"""Metric-family registry: the single source of truth for every
``tpu:`` / ``tpu_router:`` Prometheus family the stack exports.

SURVEY §4 makes the stats plane the backbone of the serving stack: the
router's scraper, the Grafana dashboard, the prometheus-adapter/HPA rule
and the CI fake engine all key off these names.  Before this registry the
contract lived in four places at once (vocabulary.py, fake_engine.py,
observability/tpu-dashboard.json, docs/observability.md) and drifted
silently — a renamed family broke dashboards without failing any test.

stackcheck rule family SC3 (tools/stackcheck/rules_metrics.py) verifies
this file against all four surfaces in both directions on every CI run:
every entry must have an emit site, and every emitted/plotted/documented
family must have an entry.  **Adding a metric family starts HERE** — see
docs/static-analysis.md#adding-a-metric-family for the checklist.

Entry shape (plain literals only; stackcheck AST-parses this file and
never imports it, so the registry stays loadable in a bare CI venv):

    "tpu:family_name": {
        "kind": "gauge" | "counter" | "histogram",
        "layer": "engine" | "router",
        "mirrors": (surfaces that MUST reference the family:
                    "fake_engine", "dashboard", "docs"),
        "source_name": optional — the literal as written in source when
                    it differs from the exposition name (prometheus_client
                    exposes Counter("x") as x_total),
        "labels": optional tuple of label names,
        "help": one-line meaning,
    }

Histogram families expose ``<name>_bucket/_sum/_count`` series; the
registry stores the base name and stackcheck normalizes suffixes.
"""

from __future__ import annotations

REGISTRY = {
    # -- engine gauges (vocabulary.py, rendered by api_server + fake) ------
    "tpu:num_requests_running": {
        "kind": "gauge", "layer": "engine",
        "mirrors": ("fake_engine", "dashboard", "docs"),
        "help": "Sequences in the running (decode) set",
    },
    "tpu:num_requests_waiting": {
        "kind": "gauge", "layer": "engine",
        "mirrors": ("fake_engine", "dashboard", "docs"),
        "help": "Waiting + preempted queue depth (the HPA signal)",
    },
    "tpu:hbm_kv_usage_perc": {
        "kind": "gauge", "layer": "engine",
        "mirrors": ("fake_engine", "dashboard", "docs"),
        "help": "Paged-KV HBM pool usage (0-1)",
    },
    "tpu:prefix_cache_hit_rate": {
        "kind": "gauge", "layer": "engine",
        "mirrors": ("fake_engine", "dashboard", "docs"),
        "help": "Rolling prefix-cache hit rate (0-1)",
    },
    "tpu:host_kv_usage_perc": {
        "kind": "gauge", "layer": "engine",
        "mirrors": ("fake_engine", "dashboard", "docs"),
        "help": "Host-DRAM offload tier usage (0-1)",
    },
    "tpu:duty_cycle": {
        "kind": "gauge", "layer": "engine",
        "mirrors": ("fake_engine", "dashboard", "docs"),
        "help": "Busy fraction of the trailing window (TPU utilization)",
    },
    "tpu:decode_host_gap_ms": {
        "kind": "gauge", "layer": "engine",
        "mirrors": ("fake_engine", "dashboard", "docs"),
        "help": "Mean host-side serialization per decode step (pipeline "
                "health; ~0 with one-step lookahead active)",
    },
    "tpu:loaded_loras": {
        "kind": "gauge", "layer": "engine",
        "mirrors": ("fake_engine", "dashboard", "docs"),
        "help": "Live LoRA adapters",
    },
    "tpu:kv_prefetch_inflight": {
        "kind": "gauge", "layer": "engine",
        "mirrors": ("fake_engine", "dashboard", "docs"),
        "help": "Remote chain fetches currently in flight",
    },
    "tpu:last_step_age_seconds": {
        "kind": "gauge", "layer": "engine",
        "mirrors": ("fake_engine", "dashboard", "docs"),
        "help": "Step-loop watchdog age; /health fails past step_watchdog_s",
    },
    "tpu:queued_prompt_tokens": {
        "kind": "gauge", "layer": "engine",
        "mirrors": ("fake_engine", "dashboard", "docs"),
        "help": "Prompt tokens held by waiting+preempted sequences (the "
                "bound admission enforces)",
    },
    "tpu:prefix_cache_blocks": {
        "kind": "gauge", "layer": "engine",
        "mirrors": ("fake_engine", "dashboard", "docs"),
        "help": "Content-valid blocks resident in the prefix cache (the "
                "truth the router's popularity view reconciles its "
                "owner map against: a collapse to ~0 means the engine "
                "restarted and its cache is empty)",
    },
    # -- engine counters ---------------------------------------------------
    "tpu:prefix_cache_hit_tokens_total": {
        "kind": "counter", "layer": "engine",
        "mirrors": ("fake_engine", "dashboard", "docs"),
        "help": "Prompt tokens served from the prefix cache since boot "
                "(fleet KV hit rate = sum hit / sum query across "
                "backends — the BASELINE.md north-star metric)",
    },
    "tpu:prefix_cache_query_tokens_total": {
        "kind": "counter", "layer": "engine",
        "mirrors": ("fake_engine", "dashboard", "docs"),
        "help": "Prompt tokens queried against the prefix cache since "
                "boot (the hit-rate denominator)",
    },
    "tpu:total_prompt_tokens": {
        "kind": "counter", "layer": "engine",
        "mirrors": ("fake_engine", "dashboard", "docs"),
        "help": "Prompt tokens prefilled since boot",
    },
    "tpu:total_generated_tokens": {
        "kind": "counter", "layer": "engine",
        "mirrors": ("fake_engine", "dashboard", "docs"),
        "help": "Tokens sampled since boot",
    },
    "tpu:total_finished_requests": {
        "kind": "counter", "layer": "engine",
        "mirrors": ("fake_engine", "dashboard", "docs"),
        "help": "Requests finished since boot",
    },
    "tpu:num_preemptions": {
        "kind": "counter", "layer": "engine",
        "mirrors": ("fake_engine", "dashboard", "docs"),
        "help": "Sequences preempted under pool pressure",
    },
    "tpu:remote_prefix_blocks_fetched": {
        "kind": "counter", "layer": "engine",
        "mirrors": ("fake_engine", "dashboard", "docs"),
        "help": "KV blocks imported from the shared store (disagg_role)",
    },
    "tpu:remote_prefix_blocks_exported": {
        "kind": "counter", "layer": "engine",
        "mirrors": ("fake_engine", "dashboard", "docs"),
        "help": "KV blocks pushed to the shared store (disagg_role)",
    },
    "tpu:disagg_prefill_primes_total": {
        "kind": "counter", "layer": "engine",
        "mirrors": ("fake_engine", "dashboard", "docs"),
        "help": "Disagg prefill-phase prime completions served (prefill "
                "ran, chain eagerly exported, handoff token returned)",
    },
    "tpu:disagg_handoff_hits_total": {
        "kind": "counter", "layer": "engine",
        "mirrors": ("fake_engine", "dashboard", "docs"),
        "help": "Decode-phase handoffs whose prefetched chain covered the "
                "whole prompt (decode executed no prompt tokens)",
    },
    "tpu:disagg_handoff_misses_total": {
        "kind": "counter", "layer": "engine",
        "mirrors": ("fake_engine", "dashboard", "docs"),
        "help": "Decode-phase handoffs admitted without a full chain "
                "import (prefill recomputed locally — in-place fused "
                "fallback)",
    },
    "tpu:spec_tokens_drafted": {
        "kind": "counter", "layer": "engine",
        "mirrors": ("fake_engine", "dashboard", "docs"),
        "help": "N-gram speculative tokens drafted",
    },
    "tpu:spec_tokens_accepted": {
        "kind": "counter", "layer": "engine",
        "mirrors": ("fake_engine", "dashboard", "docs"),
        "help": "N-gram speculative tokens accepted (rate = accepted/drafted)",
    },
    "tpu:prefill_chunk_tokens": {
        "kind": "counter", "layer": "engine",
        "mirrors": ("fake_engine", "dashboard", "docs"),
        "help": "Prompt tokens prefilled inside fused mixed steps",
    },
    "tpu:kv_prefetch_hit": {
        "kind": "counter", "layer": "engine",
        "mirrors": ("fake_engine", "dashboard", "docs"),
        "help": "KV blocks imported into the prefix cache by remote prefetch",
    },
    "tpu:kv_prefetch_waste": {
        "kind": "counter", "layer": "engine",
        "mirrors": ("fake_engine", "dashboard", "docs"),
        "help": "Prefetched KV blocks fetched then dropped unused",
    },
    "tpu:admission_rejected_total": {
        "kind": "counter", "layer": "engine",
        "mirrors": ("fake_engine", "dashboard", "docs"),
        "help": "Structured 429s from bounded admission",
    },
    "tpu:deadline_expired_total": {
        "kind": "counter", "layer": "engine",
        "mirrors": ("fake_engine", "dashboard", "docs"),
        "help": "Requests shed/aborted on an expired client deadline",
    },
    "tpu:multistep_fallback_total": {
        "kind": "counter", "layer": "engine", "labels": ("reason",),
        "mirrors": ("fake_engine", "dashboard", "docs"),
        "help": "K-step decode-window dispatches dropped to single-step "
                "because a co-scheduled request needed host-sampled "
                "features (reason: logprobs | logit_bias | guided) or "
                "because a waiting prompt forced K=1 admission cadence "
                "and the mixed K-step window could not serve it — split "
                "by WHY the mixed window declined (reason: bucket_mismatch "
                "— the head chunk fit no static chunk bucket; "
                "pool_pressure — the KV pool could not hold the chunk; "
                "waiting_head — residual decline, e.g. mixed windows off "
                "or an unpackable final chunk; draft_pool — the draft "
                "model's dedicated KV pool could not cover the batch, so "
                "the window ran plain instead of speculative)",
    },
    "tpu:mixed_window_chunk_tokens_total": {
        "kind": "counter", "layer": "engine",
        "mirrors": ("fake_engine", "dashboard", "docs"),
        "help": "Prompt tokens whose prefill chunks rode the "
                "device-resident decode scan (mixed K-step windows) — "
                "the subset of tpu:prefill_chunk_tokens that paid no "
                "per-chunk host round-trip",
    },
    "tpu:mixed_window_prompts_per_window": {
        "kind": "histogram", "layer": "engine",
        "mirrors": ("fake_engine", "dashboard", "docs"),
        "help": "Distinct prompts whose chunks rode each mixed K-step "
                "window (packed multi-prompt windows) — mass above "
                "bucket 1 is queue depth converted into device "
                "utilization",
    },
    "tpu:encode_texts_total": {
        "kind": "counter", "layer": "engine",
        "mirrors": ("fake_engine", "dashboard", "docs"),
        "help": "Texts embedded via the step thread's [B, T]-bucketed "
                "encode batches (the batched embed/rerank/score lane)",
    },
    "tpu:encode_queue_depth": {
        "kind": "gauge", "layer": "engine",
        "mirrors": ("fake_engine", "dashboard", "docs"),
        "help": "Texts queued for the encode lane (the depth encode "
                "admission bounds; the step thread drains one batch per "
                "window boundary while generation is live)",
    },
    "tpu:encode_batch_size": {
        "kind": "histogram", "layer": "engine",
        "mirrors": ("fake_engine", "dashboard", "docs"),
        "help": "Actual texts per encode batch — mass near the top "
                "bucket means embed traffic is coalescing; mass stuck "
                "at 1 under load means it arrives too sparse to batch",
    },
    "tpu:encode_seconds": {
        "kind": "histogram", "layer": "engine",
        "mirrors": ("fake_engine", "dashboard", "docs"),
        "help": "Wall seconds per [B, T]-bucketed encode batch "
                "(dispatch through device sync, observed on the step "
                "thread)",
    },
    "tpu:window_transfer_overlap_seconds_total": {
        "kind": "counter", "layer": "engine",
        "mirrors": ("fake_engine", "dashboard", "docs"),
        "help": "Seconds of host<->device transfer work issued while "
                "the device was busy with an in-flight window (H2D "
                "chunk staging for chained windows + D2H offload "
                "gathers under the scan) — stalls the overlap dispatch "
                "avoided",
    },
    "tpu:spec_window_tokens_total": {
        "kind": "counter", "layer": "engine", "labels": ("outcome", "drafter"),
        "mirrors": ("fake_engine", "dashboard", "docs"),
        "help": "Fused speculative-window outcomes (outcome: accepted | "
                "rejected | wasted) — draft tokens the in-scan verifier "
                "accepted/rejected, and fused-window tokens emitted but "
                "undeliverable at collect — split by the proposal source "
                "(drafter: ngram — prompt-lookup from the carried history "
                "buffer; model — the tiny draft model riding the scan); "
                "acceptance rate per drafter is accepted / (accepted + "
                "rejected) over this family",
    },
    "tpu:spec_draft_fraction_seconds": {
        "kind": "counter", "layer": "engine",
        "mirrors": ("fake_engine", "dashboard", "docs"),
        "help": "Scan wall-time attributed to the draft model's forwards "
                "inside fused speculative windows (static cost-model "
                "split of collect wait: draft rows x draft params vs "
                "verify rows x target params, prime amortized) — the "
                "speculation overhead the acceptance rate must pay for; "
                "the ngram drafter accrues zero here",
    },
    "tpu:multistep_wasted_tokens_total": {
        "kind": "counter", "layer": "engine",
        "mirrors": ("fake_engine", "dashboard", "docs"),
        "help": "Window tokens emitted but undeliverable (abort / "
                "out-of-band finish mid-window; device stop-mask keeps "
                "ordinary stops at zero waste)",
    },
    "tpu:kv_wire_bytes_total": {
        "kind": "counter", "layer": "engine", "labels": ("tier", "format"),
        "mirrors": ("fake_engine", "dashboard", "docs"),
        "help": "KV snapshot bytes crossing a tier boundary (tier: host "
                "| remote) by wire representation (format: dense | int8 "
                "— int8 is the native quantized (data, scale) wire; a "
                "quantized-cache fleet stuck on dense is paying the "
                "retired fp32 round-trip)",
    },
    "tpu:kv_snapshot_format_total": {
        "kind": "counter", "layer": "engine", "labels": ("version",),
        "mirrors": ("fake_engine", "dashboard", "docs"),
        "help": "KV snapshots encoded onto the kvserver wire by serde "
                "version (v1: legacy untagged dense fp32; v2: tagged "
                "int8 data + fp32 scales — kvserver/protocol.py)",
    },
    "tpu:lockstep_member_last_ack_seconds": {
        "kind": "gauge", "layer": "engine", "labels": ("member",),
        "mirrors": ("fake_engine", "dashboard", "docs"),
        "help": "Per-member seconds since the follower's lockstep acks "
                "last advanced (leader of a multi-host slice group; a "
                "member frozen near --slice-member-timeout-s is about "
                "to fail the whole slice's /health)",
    },
    "tpu:lockstep_group_epoch": {
        "kind": "gauge", "layer": "engine",
        "mirrors": ("fake_engine", "dashboard", "docs"),
        "help": "Slice group epoch (leader boot nonce carried in every "
                "lockstep event batch; strictly larger after every "
                "group restart — a step in this line IS a restart "
                "marker, and the split-brain guard's ordering)",
    },
    "tpu:lockstep_member_failures_total": {
        "kind": "counter", "layer": "engine", "labels": ("reason",),
        "mirrors": ("fake_engine", "dashboard", "docs"),
        "help": "Slice members declared failed (reason: member_silent — "
                "acks stopped past the member timeout; epoch_mismatch — "
                "a member observed a different group incarnation); each "
                "failure restarts the whole group in parallel",
    },
    "tpu:slice_drain_relays_total": {
        "kind": "counter", "layer": "engine",
        "mirrors": ("fake_engine", "dashboard", "docs"),
        "help": "Follower-initiated drains relayed to the leader "
                "(preStop/SIGTERM on a follower drains the WHOLE slice "
                "through the leader; followers keep stepping until the "
                "group shutdown so in-flight streams finish)",
    },
    "tpu:compile_seconds_total": {
        "kind": "counter", "layer": "engine", "labels": ("executable",),
        "mirrors": ("fake_engine", "dashboard", "docs"),
        "help": "Seconds spent in XLA trace+compile per executable shape "
                "key (jit entry point + compact arg-shape signature) — "
                "the compile tax behind first-request TTFT outliers; a "
                "growing series under steady traffic means live shapes "
                "are still missing from warmup coverage "
                "(GET /debug/compiles)",
    },
    "tpu:compiled_shapes": {
        "kind": "gauge", "layer": "engine",
        "mirrors": ("fake_engine", "dashboard", "docs"),
        "help": "Distinct executable shape keys compiled since boot; "
                "read against the config-derived inventory in "
                "GET /debug/compiles for warmup coverage",
    },
    "tpu:obs_trace_dropped_total": {
        "kind": "counter", "layer": "engine",
        "mirrors": ("fake_engine", "dashboard", "docs"),
        "help": "Completed trace records evicted from the /debug/requests "
                "ring by the count or byte bound (obs.trace_ring_size / "
                "obs.trace_ring_bytes) — drops are visible, not silent",
    },
    # -- engine request-level histograms (obs layer) -----------------------
    "tpu:ttft_seconds": {
        "kind": "histogram", "layer": "engine",
        "mirrors": ("fake_engine", "dashboard", "docs"),
        "help": "Per-request time to first token",
    },
    "tpu:itl_seconds": {
        "kind": "histogram", "layer": "engine",
        "mirrors": ("fake_engine", "dashboard", "docs"),
        "help": "Inter-token latency (one observation per token gap)",
    },
    "tpu:e2e_latency_seconds": {
        "kind": "histogram", "layer": "engine",
        "mirrors": ("fake_engine", "dashboard", "docs"),
        "help": "Per-request end-to-end latency",
    },
    "tpu:queue_time_seconds": {
        "kind": "histogram", "layer": "engine",
        "mirrors": ("fake_engine", "dashboard", "docs"),
        "help": "Admission -> first schedule",
    },
    "tpu:prefill_time_seconds": {
        "kind": "histogram", "layer": "engine",
        "mirrors": ("fake_engine", "dashboard", "docs"),
        "help": "Prefill phase per request",
    },
    "tpu:decode_time_seconds": {
        "kind": "histogram", "layer": "engine",
        "mirrors": ("fake_engine", "dashboard", "docs"),
        "help": "Decode phase per request",
    },
    "tpu:detokenize_time_seconds": {
        "kind": "histogram", "layer": "engine",
        "mirrors": ("fake_engine", "dashboard", "docs"),
        "help": "Accumulated host detokenize cost per request",
    },
    # -- engine step-phase histograms --------------------------------------
    "tpu:step_schedule_seconds": {
        "kind": "histogram", "layer": "engine",
        "mirrors": ("fake_engine", "dashboard", "docs"),
        "help": "Scheduler planning time per step",
    },
    "tpu:step_dispatch_seconds": {
        "kind": "histogram", "layer": "engine",
        "mirrors": ("fake_engine", "dashboard", "docs"),
        "help": "Host-side H2D dispatch time per pipelined step",
    },
    "tpu:step_collect_seconds": {
        "kind": "histogram", "layer": "engine",
        "mirrors": ("fake_engine", "dashboard", "docs"),
        "help": "Device collect/readback wait per step",
    },
    "tpu:step_sample_seconds": {
        "kind": "histogram", "layer": "engine",
        "mirrors": ("fake_engine", "dashboard", "docs"),
        "help": "Sample post-process time per step",
    },
    "tpu:step_mixed_seconds": {
        "kind": "histogram", "layer": "engine",
        "mirrors": ("fake_engine", "dashboard", "docs"),
        "help": "End-to-end wall time of fused mixed decode+prefill steps",
    },
    # -- async KV transfer-plane histograms --------------------------------
    "tpu:remote_kv_fetch_seconds": {
        "kind": "histogram", "layer": "engine",
        "mirrors": ("fake_engine", "dashboard", "docs"),
        "help": "Shared-store round-trip per MGET chain fetch / restore GET "
                "(observed on fetcher threads)",
    },
    "tpu:offload_stage_seconds": {
        "kind": "histogram", "layer": "engine",
        "mirrors": ("fake_engine", "dashboard", "docs"),
        "help": "Preemption-snapshot staging, gather dispatch -> host copy "
                "(observed on the stager's writer thread)",
    },
    # -- router gauges (prometheus_client, labeled by server) --------------
    "tpu_router:current_qps": {
        "kind": "gauge", "layer": "router", "labels": ("server",),
        "mirrors": ("dashboard", "docs"),
        "help": "Sliding-window QPS per backend",
    },
    "tpu_router:avg_ttft": {
        "kind": "gauge", "layer": "router", "labels": ("server",),
        "mirrors": ("dashboard", "docs"),
        "help": "Average TTFT per backend (window)",
    },
    "tpu_router:avg_latency": {
        "kind": "gauge", "layer": "router", "labels": ("server",),
        "mirrors": ("dashboard", "docs"),
        "help": "Average e2e latency per backend (window)",
    },
    "tpu_router:avg_itl": {
        "kind": "gauge", "layer": "router", "labels": ("server",),
        "mirrors": ("dashboard", "docs"),
        "help": "Average inter-token latency per backend (window)",
    },
    "tpu_router:avg_decoding_length": {
        "kind": "gauge", "layer": "router", "labels": ("server",),
        "mirrors": ("dashboard", "docs"),
        "help": "Average streamed chunks per request",
    },
    "tpu_router:queueing_delay_seconds": {
        "kind": "gauge", "layer": "router", "labels": ("server",),
        "mirrors": ("dashboard", "docs"),
        "help": "Average router-side queueing delay (window)",
    },
    "tpu_router:num_prefill_requests": {
        "kind": "gauge", "layer": "router", "labels": ("server",),
        "mirrors": ("dashboard", "docs"),
        "help": "Requests awaiting first token per backend",
    },
    "tpu_router:num_decoding_requests": {
        "kind": "gauge", "layer": "router", "labels": ("server",),
        "mirrors": ("dashboard", "docs"),
        "help": "Requests streaming tokens per backend",
    },
    "tpu_router:num_requests_finished": {
        "kind": "gauge", "layer": "router", "labels": ("server",),
        "mirrors": ("dashboard", "docs"),
        "help": "Completed requests per backend",
    },
    "tpu_router:num_requests_uncompleted": {
        "kind": "gauge", "layer": "router", "labels": ("server",),
        "mirrors": ("dashboard", "docs"),
        "help": "In-flight requests per backend",
    },
    "tpu_router:healthy_pods_total": {
        "kind": "gauge", "layer": "router", "labels": ("model",),
        "mirrors": ("dashboard", "docs"),
        "help": "Healthy serving-engine endpoints per model",
    },
    "tpu_router:engine_hbm_kv_usage_perc": {
        "kind": "gauge", "layer": "router", "labels": ("server",),
        "mirrors": ("docs",),
        "help": "Scraped engine KV usage re-exported per backend",
    },
    "tpu_router:engine_prefix_cache_hit_rate": {
        "kind": "gauge", "layer": "router", "labels": ("server",),
        "mirrors": ("docs",),
        "help": "Scraped engine prefix hit rate re-exported per backend",
    },
    "tpu_router:engine_num_requests_waiting": {
        "kind": "gauge", "layer": "router", "labels": ("server",),
        "mirrors": ("docs",),
        "help": "Scraped engine queue depth re-exported per backend",
    },
    "tpu_router:ttft_clean_p95_seconds": {
        "kind": "gauge", "layer": "router", "labels": ("server",),
        "mirrors": ("dashboard", "docs"),
        "help": "Compile-excluded TTFT p95 per backend (window): TTFT "
                "samples whose first chunk carried the engine's "
                "compile=true taint are excluded, separating steady-state "
                "latency from XLA warmup outliers (compare against "
                "tpu_router:ttft_seconds p95 for the compile tax)",
    },
    "tpu_router:circuit_state": {
        "kind": "gauge", "layer": "router", "labels": ("server",),
        "mirrors": ("dashboard", "docs"),
        "help": "Per-backend breaker state (0=closed, 1=half-open, 2=open)",
    },
    # -- fleet-level admission control (router/capacity.py) ----------------
    "tpu_router:fleet_headroom_slots": {
        "kind": "gauge", "layer": "router", "labels": ("pool",),
        "mirrors": ("dashboard", "docs"),
        "help": "Capacity-model fleet headroom in spare request slots per "
                "admission pool (fleet, or prefill/decode/encode under "
                "role pools — the encode lane's embed/rerank/score "
                "traffic is admitted against its own pool's headroom, so "
                "an embed burst cannot starve generation); the "
                "prom-adapter exposes it for HPA",
    },
    "tpu_router:backend_capacity_slots": {
        "kind": "gauge", "layer": "router", "labels": ("server",),
        "mirrors": ("docs",),
        "help": "Learned max useful concurrency per backend (the online "
                "capacity model's slot estimate)",
    },
    "tpu_router:backend_capacity_score": {
        "kind": "gauge", "layer": "router", "labels": ("server",),
        "mirrors": ("dashboard", "docs"),
        "help": "Free-capacity fraction per backend (1 = idle, 0 = "
                "saturated or inside an engine-429 Retry-After window)",
    },
    # -- fleet prefix-popularity view (routing kv_aware_popularity) --------
    "tpu_router:prefix_hot_total": {
        "kind": "counter", "layer": "router",
        "mirrors": ("dashboard", "docs"),
        "help": "Prefixes promoted to HOT by the popularity view (their "
                "decayed request frequency crossed the threshold; each "
                "is served by a replica set from then on)",
    },
    "tpu_router:prefix_replica_set_size": {
        "kind": "gauge", "layer": "router",
        "mirrors": ("dashboard", "docs"),
        "help": "Largest live hot-prefix replica set — the shared system "
                "prompt's replication degree (grows under member load, "
                "shrinks by TTL decay)",
    },
    "tpu_router:fleet_prefix_hit_rate": {
        "kind": "gauge", "layer": "router",
        "mirrors": ("dashboard", "docs"),
        "help": "Fleet-wide token-weighted KV prefix hit rate from the "
                "engines' scraped tpu:prefix_cache_{hit,query}_tokens_"
                "total truth counters (the BASELINE.md headline metric, "
                "at one scrape point)",
    },
    "tpu_router:semantic_cache_size": {
        "kind": "gauge", "layer": "router",
        "mirrors": ("dashboard", "docs"),
        "help": "Entries resident in the semantic cache",
    },
    # -- router counters (prometheus_client exposes Counter(x) as x_total) -
    "tpu_router:deadline_expired_total": {
        "kind": "counter", "layer": "router",
        "mirrors": ("dashboard", "docs"),
        "help": "Requests shed at the router on an expired deadline",
    },
    "tpu_router:fleet_admission_rejected_total": {
        "kind": "counter", "layer": "router", "labels": ("reason",),
        "mirrors": ("dashboard", "docs"),
        "help": "Requests shed at the router by fleet-level admission "
                "control (reason: no_headroom | low_priority) — in a "
                "healthy fleet these strictly precede any engine-side 429",
    },
    "tpu_router:semantic_cache_hits_total": {
        "kind": "counter", "layer": "router",
        "source_name": "tpu_router:semantic_cache_hits",
        "mirrors": ("dashboard", "docs"),
        "help": "Semantic cache hits served (chat experimental cache + "
                "the encode-lane cache fronting /v1/embeddings, rerank "
                "and score — an exact hit answers with the stored "
                "response bytes and zero engine work)",
    },
    "tpu_router:semantic_cache_misses_total": {
        "kind": "counter", "layer": "router",
        "source_name": "tpu_router:semantic_cache_misses",
        "mirrors": ("dashboard", "docs"),
        "help": "Semantic cache lookups that missed (chat experimental "
                "cache + the encode-lane cache)",
    },
    "tpu_router:pii_requests_scanned_total": {
        "kind": "counter", "layer": "router",
        "source_name": "tpu_router:pii_requests_scanned",
        "mirrors": ("dashboard", "docs"),
        "help": "Requests scanned by the PII middleware",
    },
    "tpu_router:pii_requests_blocked_total": {
        "kind": "counter", "layer": "router",
        "source_name": "tpu_router:pii_requests_blocked",
        "mirrors": ("dashboard", "docs"),
        "help": "Requests blocked because PII was detected",
    },
    "tpu_router:pii_detections_total": {
        "kind": "counter", "layer": "router", "labels": ("pii_type",),
        "source_name": "tpu_router:pii_detections",
        "mirrors": ("dashboard", "docs"),
        "help": "PII entities detected in request bodies",
    },
    "tpu_router:obs_trace_dropped_total": {
        "kind": "counter", "layer": "router",
        "source_name": "tpu_router:obs_trace_dropped",
        "mirrors": ("dashboard", "docs"),
        "help": "Completed trace records evicted from the router's "
                "/debug/requests ring by the count or byte bound "
                "(--trace-ring-size / --trace-ring-bytes)",
    },
    "tpu_router:disagg_fallback_total": {
        "kind": "counter", "layer": "router", "labels": ("reason",),
        "mirrors": ("dashboard", "docs"),
        "help": "Two-phase disagg requests degraded to the fused path "
                "(reason: prefill_pool_empty | prefill_breaker_open | "
                "decode_pool_empty | prime_failed | handoff_unexported | "
                "prefix_miss)",
    },
    "tpu_router:disagg_requests_total": {
        "kind": "counter", "layer": "router", "labels": ("role",),
        "mirrors": ("dashboard", "docs"),
        "help": "Requests routed by the disagg policy, by phase role "
                "(prefill | decode | fused)",
    },
    "tpu_router:disagg_handoff_seconds": {
        "kind": "histogram", "layer": "router",
        "mirrors": ("dashboard", "docs"),
        "help": "Disagg prefill-phase latency: prime connect + engine "
                "prefill + eager export + handoff response",
    },
    # -- router latency histograms (custom render, labeled by server) ------
    "tpu_router:ttft_seconds": {
        "kind": "histogram", "layer": "router", "labels": ("server",),
        "mirrors": ("dashboard", "docs"),
        "help": "Router-observed TTFT per backend",
    },
    "tpu_router:itl_seconds": {
        "kind": "histogram", "layer": "router", "labels": ("server",),
        "mirrors": ("dashboard", "docs"),
        "help": "Router-observed inter-token latency per backend",
    },
    "tpu_router:e2e_latency_seconds": {
        "kind": "histogram", "layer": "router", "labels": ("server",),
        "mirrors": ("dashboard", "docs"),
        "help": "Router-observed e2e latency per backend",
    },
    "tpu_router:request_queueing_seconds": {
        "kind": "histogram", "layer": "router", "labels": ("server",),
        "mirrors": ("dashboard", "docs"),
        "help": "Router-side queueing before backend connect",
    },
}
