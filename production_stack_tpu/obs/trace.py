"""Self-contained request tracing: spans, per-request timelines, ring buffer.

No OpenTelemetry dependency — TPU serving images don't ship it, and the
stack only needs (a) W3C ``traceparent`` propagation so router and engine
timelines join under one trace id, and (b) a bounded in-memory ring of
completed request timelines served at ``GET /debug/requests``.  ``to_otlp``
emits OTLP-shaped JSON for anyone who wants to forward a timeline into a
real collector.

Thread-safety: the engine records spans from its step thread while the
HTTP server reads from the event loop; every mutation holds the tracer
lock.  All buffers are bounded (active map + completed ring), so tracing
cannot grow without limit under sustained traffic.
"""

from __future__ import annotations

import dataclasses
import json
import secrets
import threading
import time
from collections import OrderedDict, deque
from typing import Deque, Dict, List, Optional


def new_trace_id() -> str:
    return secrets.token_hex(16)


def new_span_id() -> str:
    return secrets.token_hex(8)


def parse_traceparent(value: Optional[str]) -> Optional[str]:
    """Extract the trace-id from a W3C traceparent header
    (``00-<32 hex trace-id>-<16 hex parent-id>-<2 hex flags>``).
    Returns None for absent/malformed headers (a malformed header must
    start a fresh trace, never 500 the request path)."""
    if not value:
        return None
    parts = value.strip().split("-")
    if len(parts) < 4:
        return None
    trace_id = parts[1].lower()
    if len(trace_id) != 32 or trace_id == "0" * 32:
        return None
    try:
        int(trace_id, 16)
    except ValueError:
        return None
    return trace_id


def make_traceparent(trace_id: str, span_id: Optional[str] = None) -> str:
    return f"00-{trace_id}-{span_id or new_span_id()}-01"


@dataclasses.dataclass
class Span:
    name: str
    start: float  # unix seconds
    end: float
    attrs: Dict = dataclasses.field(default_factory=dict)

    @property
    def duration(self) -> float:
        return max(0.0, self.end - self.start)

    def to_dict(self) -> Dict:
        d = {
            "name": self.name,
            "start": self.start,
            "end": self.end,
            "duration_s": round(self.duration, 6),
        }
        if self.attrs:
            d["attrs"] = dict(self.attrs)
        return d


@dataclasses.dataclass
class RequestTrace:
    request_id: str
    trace_id: str
    component: str  # "router" | "engine"
    start: float
    end: Optional[float] = None
    spans: List[Span] = dataclasses.field(default_factory=list)
    attrs: Dict = dataclasses.field(default_factory=dict)
    # Serialized-record size, stamped when the trace is retired to the
    # completed ring (the byte-bound accounting unit; not exported).
    approx_bytes: int = 0

    def add_span(self, name: str, start: float, end: float, **attrs) -> Span:
        span = Span(name=name, start=start, end=end, attrs=attrs)
        self.spans.append(span)
        return span

    @property
    def duration(self) -> float:
        return max(0.0, (self.end or time.time()) - self.start)

    def to_dict(self) -> Dict:
        return {
            "request_id": self.request_id,
            "trace_id": self.trace_id,
            "component": self.component,
            "start": self.start,
            "end": self.end,
            "duration_s": round(self.duration, 6),
            "attrs": dict(self.attrs),
            "spans": [s.to_dict() for s in sorted(self.spans, key=lambda s: s.start)],
        }

    def to_otlp(self) -> Dict:
        """OTLP/JSON-shaped export of this timeline (one resourceSpans
        entry; span/parent ids are freshly minted — only the trace id is
        load-bearing for cross-component joins)."""

        def nanos(t: float) -> str:
            return str(int(t * 1e9))

        return {
            "resourceSpans": [{
                "resource": {"attributes": [
                    {"key": "service.name",
                     "value": {"stringValue": f"tpu-{self.component}"}},
                ]},
                "scopeSpans": [{
                    "scope": {"name": "production_stack_tpu.obs"},
                    "spans": [
                        {
                            "traceId": self.trace_id,
                            "spanId": new_span_id(),
                            "name": span.name,
                            "startTimeUnixNano": nanos(span.start),
                            "endTimeUnixNano": nanos(span.end),
                            "attributes": [
                                {"key": str(k), "value": {"stringValue": str(v)}}
                                for k, v in span.attrs.items()
                            ],
                        }
                        for span in self.spans
                    ],
                }],
            }]
        }


class Tracer:
    """Bounded per-component trace store.

    ``start`` opens an active trace; ``finish`` moves it to the completed
    ring (newest first).  ``add_span`` accepts spans for active AND
    recently-completed traces — the engine finishes a request's trace on
    its step thread while the server still owes the detokenize span.
    A disabled tracer is all no-ops returning None, so gated call sites
    stay branch-cheap.
    """

    # Active-map bound: requests that never finish (leaked ids from crashed
    # peers) must not grow memory; oldest actives are dropped past this.
    MAX_ACTIVE_FACTOR = 4

    def __init__(
        self,
        component: str,
        enabled: bool = True,
        ring_size: int = 256,
        ring_bytes: Optional[int] = None,
    ):
        self.component = component
        self.enabled = enabled
        self.ring_size = max(1, int(ring_size))
        # Byte bound on the completed ring: a long-prompt burst produces
        # records hundreds of times larger than a short one, so a
        # count-only cap does not bound resident memory.  None/0 = count
        # bound only.  Evictions (either bound) increment ``dropped`` so
        # drops are visible (tpu:obs_trace_dropped_total), not silent.
        self.ring_bytes = int(ring_bytes) if ring_bytes else None
        self._completed_bytes = 0
        self.dropped = 0
        self._active: "OrderedDict[str, RequestTrace]" = OrderedDict()
        self._completed: Deque[RequestTrace] = deque()
        self._lock = threading.Lock()

    @staticmethod
    def _approx_bytes(trace: RequestTrace) -> int:
        """Serialized size of one completed record — the unit the byte
        bound accumulates.  Cost is paid once per request at finish, off
        the per-token path."""
        try:
            return len(json.dumps(trace.to_dict(), default=str))
        except (TypeError, ValueError):
            return 1024

    def _retire_locked(self, trace: RequestTrace) -> None:
        """Move one finished trace into the completed ring, evicting the
        oldest records past the count bound and the byte bound (always
        keeping the newest).  Lock held by the caller."""
        nbytes = self._approx_bytes(trace)
        trace.approx_bytes = nbytes
        self._completed.appendleft(trace)
        self._completed_bytes += nbytes
        while len(self._completed) > self.ring_size:
            old = self._completed.pop()
            self._completed_bytes -= old.approx_bytes
            self.dropped += 1
        while (
            self.ring_bytes
            and self._completed_bytes > self.ring_bytes
            and len(self._completed) > 1
        ):
            old = self._completed.pop()
            self._completed_bytes -= old.approx_bytes
            self.dropped += 1

    def start(
        self,
        request_id: str,
        trace_id: Optional[str] = None,
        attrs: Optional[Dict] = None,
        start: Optional[float] = None,
    ) -> Optional[RequestTrace]:
        if not self.enabled:
            return None
        trace = RequestTrace(
            request_id=request_id,
            trace_id=trace_id or new_trace_id(),
            component=self.component,
            start=start if start is not None else time.time(),
            attrs=dict(attrs or {}),
        )
        with self._lock:
            # Duplicate in-flight id (retrying/buggy client reusing an
            # X-Request-Id): retire the older timeline to the ring marked
            # superseded rather than silently merging two requests' spans
            # into one timeline.  Lifecycle events keyed by this id now
            # attribute to the newest trace — ambiguous by construction,
            # but defined, and the first timeline stays inspectable.
            prev = self._active.pop(request_id, None)
            if prev is not None:
                prev.end = trace.start
                prev.attrs["superseded"] = True
                self._retire_locked(prev)
            self._active[request_id] = trace
            while len(self._active) > self.MAX_ACTIVE_FACTOR * self.ring_size:
                self._active.popitem(last=False)
        return trace

    def _get_locked(self, request_id: str) -> Optional[RequestTrace]:
        trace = self._active.get(request_id)
        if trace is not None:
            return trace
        for t in self._completed:
            if t.request_id == request_id:
                return t
        return None

    def get(self, request_id: str) -> Optional[RequestTrace]:
        with self._lock:
            return self._get_locked(request_id)

    def snapshot(self, request_id: str) -> Optional[Dict]:
        """Lock-held to_dict of one trace — the ONLY safe way to read a
        trace from another thread (the engine step thread mutates
        spans/attrs of active AND recently-completed traces; an unlocked
        to_dict() can see a dict resize mid-iteration)."""
        with self._lock:
            trace = self._get_locked(request_id)
            return None if trace is None else trace.to_dict()

    def snapshots(self) -> List[Dict]:
        """Lock-held to_dict of every completed trace, newest first."""
        with self._lock:
            return [t.to_dict() for t in self._completed]

    def add_span(
        self, request_id: str, name: str, start: float, end: float, **attrs
    ) -> None:
        if not self.enabled:
            return
        trace = self.get(request_id)
        if trace is not None:
            with self._lock:
                trace.add_span(name, start, end, **attrs)

    def get_attr(self, request_id: str, key: str, default=None):
        """Lock-held read of one trace attribute (e.g. the compile taint
        the API server checks at first-token time)."""
        if not self.enabled:
            return default
        with self._lock:
            trace = self._get_locked(request_id)
            return default if trace is None else trace.attrs.get(key, default)

    def set_attrs(self, request_id: str, **attrs) -> None:
        if not self.enabled:
            return
        trace = self.get(request_id)
        if trace is not None:
            with self._lock:
                trace.attrs.update(attrs)

    def finish(
        self, request_id: str, end: Optional[float] = None, **attrs
    ) -> Optional[RequestTrace]:
        if not self.enabled:
            return None
        with self._lock:
            trace = self._active.pop(request_id, None)
            if trace is None:
                return None
            trace.end = end if end is not None else time.time()
            trace.attrs.update(attrs)
            self._retire_locked(trace)
        return trace

    def discard(self, request_id: str) -> None:
        with self._lock:
            self._active.pop(request_id, None)

    def completed(self) -> List[RequestTrace]:
        """Completed traces, newest first."""
        with self._lock:
            return list(self._completed)

    def active_count(self) -> int:
        with self._lock:
            return len(self._active)
