"""XLA compile-event tracking for the engine's jit entry points.

The windowed engine's compiled-shape inventory is a product space
(|prefill buckets| x |decode batch buckets| x O(log K) scan variants x
spec/mixed variants); first requests routinely pay multi-second compiles
that would otherwise surface only as unexplained TTFT outliers.  The
tracker wraps each ``jax.jit`` callable in a thin proxy that watches the
executable cache size across calls: a growing cache means THIS call
traced+compiled a new input shape, and the call's wall time is (almost
entirely) that compile.  Events are keyed by a compact
``name[shape-signature]`` executable key and exported as
``tpu:compile_seconds_total{executable}`` + the ``tpu:compiled_shapes``
gauge; the engine drains pending events after each dispatch to tag the
owning windows/requests ``compile=true``.

jax-free by construction (duck-typed ``_cache_size`` / shape probing), so
the module imports in the bare router/CI venv; when a wrapped callable
lacks ``_cache_size`` the proxy degrades to pass-through.

Thread-safety: wrapped callables fire on the engine step thread; the HTTP
server reads snapshots from the event loop — every mutation of the shared
maps holds ``_lock``.  Disabled, ``wrap`` returns the callable unchanged,
so the fast path keeps bare jit functions (byte-identical dispatch).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional

_SIG_MAX_CHARS = 96  # keep executable label cardinality readable


def _sig_part(x: Any, depth: int = 0) -> str:
    """Compact shape token for one argument: arrays render as
    ``dtype[d0,d1]``, weight pytrees collapse to ``params``, small tuples
    recurse one level, scalars render literally."""
    shape = getattr(x, "shape", None)
    if shape is not None:
        try:
            dims = ",".join(str(int(d)) for d in shape)
        except TypeError:
            dims = "?"
        dtype = getattr(x, "dtype", "")
        return f"{dtype}[{dims}]"
    if isinstance(x, dict):
        return "params"
    if isinstance(x, (list, tuple)):
        if depth >= 1 or len(x) > 4:
            return f"tree{len(x)}"
        return "(" + ",".join(_sig_part(v, depth + 1) for v in x) + ")"
    if isinstance(x, (bool, int, float)) or x is None:
        return repr(x)
    return type(x).__name__


def arg_signature(args: tuple, kwargs: dict) -> str:
    parts = [_sig_part(a) for a in args]
    parts.extend(f"{k}={_sig_part(v)}" for k, v in sorted(kwargs.items()))
    sig = ",".join(parts)
    if len(sig) > _SIG_MAX_CHARS:
        sig = sig[: _SIG_MAX_CHARS - 1] + "~"
    return sig


class _TrackedJit:
    """Pass-through proxy for one jit callable; detects compiles via the
    executable-cache-size delta around each call."""

    __slots__ = ("_tracker", "_name", "_fn")

    def __init__(self, tracker: "CompileTracker", name: str, fn: Callable):
        self._tracker = tracker
        self._name = name
        self._fn = fn

    # stackcheck: allow=SC201 reason=compile wall-time measurement is an observability sink; no plan state reads it (obs layer is plan-inert by contract)
    def __call__(self, *args, **kwargs):
        fn = self._fn
        try:
            before = fn._cache_size()
        except Exception:
            return fn(*args, **kwargs)
        t0 = time.time()
        out = fn(*args, **kwargs)
        try:
            grew = fn._cache_size() > before
        except Exception:
            grew = False
        if grew:
            self._tracker.record(
                self._name, arg_signature(args, kwargs), time.time() - t0
            )
        return out

    def __getattr__(self, item):
        # lower()/clear_cache()/_cache_size() etc. reach the real jit fn.
        return getattr(self._fn, item)


class CompileTracker:
    """Per-engine compile-event store + the wrap() instrumentation hook."""

    def __init__(self, enabled: bool = True):
        self.enabled = bool(enabled)
        self._lock = threading.Lock()
        # executable key -> [count, seconds]
        self._by_executable: Dict[str, list] = {}
        # events since the engine last drained (tag owning windows/spans)
        self._events: List[Dict] = []

    def wrap(self, name: str, fn: Optional[Callable]) -> Optional[Callable]:
        """Instrument one jit entry point.  Identity when disabled or fn
        is None, so the gated-off engine keeps bare callables."""
        if not self.enabled or fn is None:
            return fn
        return _TrackedJit(self, name, fn)

    def record(self, name: str, signature: str, seconds: float) -> None:
        key = f"{name}[{signature}]"
        with self._lock:
            ent = self._by_executable.setdefault(key, [0, 0.0])
            ent[0] += 1
            ent[1] += float(seconds)
            self._events.append({"executable": key, "seconds": float(seconds)})

    def drain_events(self) -> List[Dict]:
        """Events recorded since the last drain (engine step thread calls
        this after each dispatch to taint the owning window/request)."""
        if not self.enabled:
            return []
        with self._lock:
            if not self._events:
                return []
            events, self._events = self._events, []
        return events

    # -- exposition --------------------------------------------------------

    def compiled_shapes(self) -> int:
        with self._lock:
            return len(self._by_executable)

    def compile_seconds(self) -> float:
        with self._lock:
            return sum(ent[1] for ent in self._by_executable.values())

    def seconds_by_executable(self) -> Dict[str, float]:
        """{executable key: cumulative seconds} — the
        tpu:compile_seconds_total{executable} label set."""
        with self._lock:
            return {k: ent[1] for k, ent in self._by_executable.items()}

    def snapshot(self) -> List[Dict]:
        """Per-executable compile events, most expensive first."""
        with self._lock:
            rows = [
                {"executable": k, "count": ent[0],
                 "seconds": round(ent[1], 6)}
                for k, ent in self._by_executable.items()
            ]
        rows.sort(key=lambda r: -r["seconds"])
        return rows
