"""Engine-side observability hub: request tracer + latency/step histograms.

One ``EngineObs`` lives on each ``LLMEngine`` (and on the fake engine's
state, so the CI contract matches the real engine).  The engine core calls
the lifecycle hooks from its step thread; the API server starts traces
(with the router-propagated trace id) and attaches the detokenize span.

Everything is gated on ``enabled`` (config ``obs.tracing``): disabled, every
hook returns before touching any state — no histogram observes, no trace
allocations, no per-step bookkeeping — restoring the pre-tracing fast path.
"""

from __future__ import annotations

import time
from typing import Dict, Optional

from production_stack_tpu.obs.compile_tracker import CompileTracker
from production_stack_tpu.obs.flight_recorder import FlightRecorder
from production_stack_tpu.obs.histogram import (
    Histogram,
    render_histogram,
)
from production_stack_tpu.obs.trace import Tracer

# Engine step phases (host-side attribution of ONE engine step; every
# observation is per-step so the families are unit-comparable).  Keys map
# to ``tpu:step_<phase>_seconds`` histogram families (vocabulary.py):
#   schedule - scheduler planning (schedule / schedule_provisional)
#   dispatch - host work launching device execution (array build + H2D)
#   collect  - blocking device compute + sample readback
#   sample   - host sampling post-process (append, finish checks, guided)
#   mixed    - one fused decode+prefill-chunk step, wall time end to end
#              (array build + blocking device compute + both segments'
#              sampling); its _count is the number of mixed steps, so
#              rate(mixed_count)/rate(all step counts) is the fraction of
#              steps where a prompt chunked alongside live decodes.
# schedule covers every step; dispatch/collect/sample are the PIPELINED
# decode split (the steady-state hot path) — synchronous steps (prefill,
# host-state fallbacks) fuse those stages into one blocking call and
# cannot be split without lying about where the time went.  Mixed steps
# are synchronous by design and get their own family instead.
STEP_PHASES = ("schedule", "dispatch", "collect", "sample", "mixed")

# Request-level engine histograms -> ``tpu:*_seconds`` families; one
# observation per request, EXCEPT itl which observes every token gap (its
# _count is ~tokens, not requests).  detokenize_time is the request's
# TOTAL host detokenize cost (accumulated across its tokens in the API
# server) — a request-level quantity, which is why it lives here and not
# in the per-step families above.
REQUEST_HISTS = ("ttft", "itl", "e2e_latency", "queue_time", "prefill_time",
                 "decode_time", "detokenize_time")

# Async KV transfer-plane phases -> ``tpu:*_seconds`` families
# (vocabulary.TPU_KV_HISTOGRAMS).  Observed from the plane's BACKGROUND
# threads (prefetch fetchers, offload stager writer), never the step
# thread — that is the point: these families measure the store/DMA
# latency the plane keeps OFF the step loop.
#   remote_kv_fetch - one store round-trip (MGET chain fetch/restore GET)
#   offload_stage   - one staged preemption snapshot, gather dispatch ->
#                     host copy landed
KV_PHASES = ("remote_kv_fetch", "offload_stage")

# The span set a joined router+engine timeline is scored against
# (/debug/requests/{id}: phase_sum_s vs total_s).  engine.detokenize is
# accumulated host time interleaved WITH engine.decode (marked
# accumulated=True on the span): it can push phase_sum slightly above
# total for detokenize-heavy outputs, bounded by the detokenize fraction.
# The other five partition the wall clock.
PHASE_SPAN_NAMES = (
    "router.queue",
    "router.backend_connect",
    "engine.queue",
    "engine.prefill",
    "engine.decode",
    "engine.detokenize",
)


class EngineObs:
    def __init__(
        self,
        enabled: bool = True,
        ring_size: int = 256,
        ring_bytes: int = 0,
        window_ring_size: int = 1024,
    ):
        self.enabled = bool(enabled)
        self.tracer = Tracer(
            "engine", enabled=self.enabled, ring_size=ring_size,
            ring_bytes=ring_bytes,
        )
        # Window flight recorder: one record per engine dispatch
        # (GET /debug/windows, joined into /debug/requests/{id}).
        self.recorder = FlightRecorder(
            enabled=self.enabled, ring_size=window_ring_size,
        )
        # XLA compile-event tracker: the engine wraps its jit entry
        # points through this when tracing is on (GET /debug/compiles,
        # tpu:compile_seconds_total{executable}).
        self.compile_tracker = CompileTracker(enabled=self.enabled)
        # Histograms are created eagerly (fixed, small set) so /metrics
        # always renders every family — dashboards and the router scraper
        # see stable names from the first scrape.
        self.step_hists: Dict[str, Histogram] = {
            phase: Histogram() for phase in STEP_PHASES
        }
        self.request_hists: Dict[str, Histogram] = {
            name: Histogram() for name in REQUEST_HISTS
        }
        self.kv_hists: Dict[str, Histogram] = {
            name: Histogram() for name in KV_PHASES
        }

    # -- step phases (engine step thread) ----------------------------------

    def step_phase(self, phase: str, seconds: float) -> None:
        if not self.enabled:
            return
        self.step_hists[phase].observe(seconds)

    # -- KV transfer plane (prefetch/stager background threads) ------------

    def kv_phase(self, phase: str, seconds: float) -> None:
        if not self.enabled:
            return
        self.kv_hists[phase].observe(seconds)

    # -- request lifecycle (engine step thread) ----------------------------

    # stackcheck: allow=SC201 reason=observability timeline math; the whole obs layer is plan-inert by contract (tracing=False removes it entirely and greedy parity is asserted in tests)
    def on_first_scheduled(self, seq, now: Optional[float] = None) -> None:
        """First prefill chunk launched: the queue-wait span ends here."""
        if not self.enabled:
            return
        now = now if now is not None else time.time()
        self.request_hists["queue_time"].observe(now - seq.arrival_time)
        self.tracer.add_span(seq.seq_id, "engine.queue", seq.arrival_time, now)

    def on_first_token(self, seq, now: float) -> None:
        if not self.enabled:
            return
        self.request_hists["ttft"].observe(now - seq.arrival_time)
        sched = seq.first_scheduled_time
        if sched is not None:
            self.request_hists["prefill_time"].observe(now - sched)
            self.tracer.add_span(seq.seq_id, "engine.prefill", sched, now)

    def on_token_gap(self, seq, gap: float) -> None:
        if not self.enabled:
            return
        self.request_hists["itl"].observe(gap)

    # stackcheck: allow=SC201 reason=observability timeline math; the whole obs layer is plan-inert by contract (tracing=False removes it entirely and greedy parity is asserted in tests)
    def on_finish(self, seq, now: Optional[float] = None) -> None:
        """Single finish hook (called from _finish_seq_now): e2e + decode
        histograms, the decode span, and trace completion."""
        if not self.enabled:
            return
        now = now if now is not None else time.time()
        self.request_hists["e2e_latency"].observe(now - seq.arrival_time)
        first = seq.first_token_time
        if first is not None:
            self.request_hists["decode_time"].observe(now - first)
            self.tracer.add_span(seq.seq_id, "engine.decode", first, now)
        self.tracer.finish(
            seq.seq_id,
            end=now,
            finish_reason=(
                seq.finish_reason.value if seq.finish_reason else None
            ),
            num_prompt_tokens=seq.num_prompt_tokens,
            num_output_tokens=seq.num_generated,
        )

    def on_abort(self, request_id: str) -> None:
        if not self.enabled:
            return
        self.tracer.finish(request_id, aborted=True)

    # -- compile taint (engine step thread writes, server reads) -----------

    def on_compile(self, seq_ids, events, rec=None) -> None:
        """Attribute drained compile events: mark the owning window
        record compile-tainted and tag every co-scheduled request's trace
        ``compile=true`` so compile-tainted TTFT samples are separable
        from steady-state ones."""
        if not self.enabled or not events:
            return
        total = sum(e.get("seconds", 0.0) for e in events)
        self.recorder.note_compile(rec, total)
        for sid in seq_ids:
            self.tracer.set_attrs(sid, compile=True)

    def compile_tainted(self, request_id: str) -> bool:
        """Did an XLA compile fire inside this request's dispatches?  The
        API server stamps the answer into the first response chunk so the
        router can keep a compile-excluded TTFT window."""
        if not self.enabled:
            return False
        return bool(self.tracer.get_attr(request_id, "compile", False))

    # -- server-side hooks -------------------------------------------------

    def start_request(
        self, request_id: str, trace_id: Optional[str], **attrs
    ) -> None:
        if not self.enabled:
            return
        self.tracer.start(request_id, trace_id=trace_id, attrs=attrs)

    def record_detokenize(self, request_id: str, seconds: float) -> None:
        """Accumulated host detokenize time for one request, reported by
        the API server after the stream ends.  The span is anchored at the
        trace end (the work was interleaved with decode; ``accumulated``
        marks it as a duration, not a wall-clock interval)."""
        if not self.enabled:
            return
        self.request_hists["detokenize_time"].observe(seconds)
        trace = self.tracer.get(request_id)
        if trace is not None:
            anchor = trace.end if trace.end is not None else time.time()
            self.tracer.add_span(
                request_id, "engine.detokenize", anchor, anchor + seconds,
                accumulated=True,
            )

    # -- exposition --------------------------------------------------------

    def render_metrics(self) -> str:
        """Histogram families appended to the engine's /metrics body.
        Rendered even at zero observations so names are scrape-stable."""
        from production_stack_tpu.router.stats import vocabulary as vocab

        parts = []
        for name, hist in self.request_hists.items():
            parts.append(render_histogram(vocab.TPU_REQUEST_HISTOGRAMS[name], hist))
        for phase, hist in self.step_hists.items():
            parts.append(render_histogram(vocab.TPU_STEP_HISTOGRAMS[phase], hist))
        for phase, hist in self.kv_hists.items():
            parts.append(render_histogram(vocab.TPU_KV_HISTOGRAMS[phase], hist))
        return "".join(parts)

    def debug_payload(self) -> Dict:
        return {
            "enabled": self.enabled,
            # Lock-held snapshots: the step thread mutates these traces.
            "requests": self.tracer.snapshots(),
            "dropped": self.tracer.dropped,
        }

    def request_payload(self, request_id: str) -> Optional[Dict]:
        """One request's timeline with its window flight records joined in
        (/debug/requests/{id}): which windows it rode, what else shared
        them, which one stalled.  None when the trace is unknown."""
        snap = self.tracer.snapshot(request_id)
        if snap is None:
            return None
        snap["windows"] = self.recorder.for_request(request_id)
        return snap

    def windows_payload(self, seq: Optional[str] = None) -> Dict:
        """GET /debug/windows (+?seq= filter): the flight-recorder ring,
        newest first."""
        return {
            "enabled": self.enabled,
            "windows": self.recorder.snapshot(seq=seq),
            "recorded": self.recorder.windows_recorded,
            "dropped": self.recorder.dropped,
        }
