"""Semantic response cache for chat completions.

Reference counterpart: src/vllm_router/experimental/semantic_cache*/ —
SentenceTransformer embeddings (semantic_cache.py:60-75) + FAISS
IndexFlatIP with pickle persistence (db_adapters/faiss_adapter.py:38-69)
behind optional extras.  Neither sentence-transformers nor faiss ships on
TPU images, and model downloads need egress the cluster may not have — so
the default embedding here is a dependency-free hashed bag-of-ngrams
(cosine over a fixed-dimension float vector), with the same cache
semantics: threshold similarity search over (model, last-user-message)
keys, exact-match fast path, JSON-lines persistence.

numpy is the only dependency (always present: jax depends on it).
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import re
import threading
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

logger = logging.getLogger(__name__)

SEMANTIC_CACHE_SERVICE = "semantic_cache"

_WORD_RE = re.compile(r"[a-z0-9']+")


def _embed_hashed_ngrams(text: str, dim: int = 512) -> np.ndarray:
    """Deterministic embedding: hashed unigrams + bigrams, L2-normalized.
    Not a neural embedding — but monotone in lexical overlap, which is the
    property the cache needs (near-duplicate questions hit, novel ones
    miss)."""
    vec = np.zeros(dim, np.float32)
    words = _WORD_RE.findall(text.lower())
    grams = words + [f"{a}_{b}" for a, b in zip(words, words[1:])]
    for gram in grams:
        digest = hashlib.blake2b(gram.encode(), digest_size=8).digest()
        idx = int.from_bytes(digest[:4], "little") % dim
        sign = 1.0 if digest[4] & 1 else -1.0
        vec[idx] += sign
    norm = float(np.linalg.norm(vec))
    if norm > 0:
        vec /= norm
    return vec


class SemanticCache:
    """Threshold-similarity cache of non-streaming chat completions."""

    def __init__(
        self,
        threshold: float = 0.95,
        max_entries: int = 2048,
        cache_dir: Optional[str] = None,
        dim: int = 512,
    ):
        self.threshold = threshold
        self.max_entries = max_entries
        self.cache_dir = cache_dir
        self.dim = dim
        self.hits = 0
        self.misses = 0
        self._lock = threading.Lock()
        # Per-model stores: list of (vector, key_text, response_bytes).
        self._entries: Dict[str, List[Tuple[np.ndarray, str, bytes]]] = {}
        self._exact: Dict[Tuple[str, str], bytes] = {}
        if cache_dir:
            os.makedirs(cache_dir, exist_ok=True)
            self._load()

    # -- persistence (JSON lines; the reference pickles a FAISS index) -----

    def _store_path(self) -> str:
        return os.path.join(self.cache_dir, "semantic_cache.jsonl")

    def _load(self) -> None:
        path = self._store_path()
        if not os.path.exists(path):
            return
        count = 0
        with open(path) as f:
            for line in f:
                try:
                    row = json.loads(line)
                    self._insert(
                        row["model"], row["key"],
                        row["response"].encode(), persist=False,
                    )
                    count += 1
                except (KeyError, ValueError):
                    continue
        logger.info("Semantic cache: loaded %d entries from %s", count, path)

    def _persist(self, model: str, key: str, response: bytes) -> None:
        if not self.cache_dir:
            return
        with open(self._store_path(), "a") as f:
            f.write(json.dumps({
                "model": model, "key": key,
                "response": response.decode("utf-8", "replace"),
            }) + "\n")

    # -- core --------------------------------------------------------------

    @staticmethod
    def request_key(body: Dict[str, Any]) -> Optional[str]:
        """Cache key: the conversation's user messages (the reference keys
        on the last user message only, semantic_cache.py:142-156; including
        the full user history avoids cross-conversation false hits)."""
        messages = body.get("messages")
        if not isinstance(messages, list) or not messages:
            return None
        user_parts = [
            str(m.get("content", ""))
            for m in messages
            if isinstance(m, dict) and m.get("role") == "user"
        ]
        if not user_parts:
            return None
        return "\n".join(user_parts)

    def lookup(self, model: str, key: str) -> Optional[bytes]:
        with self._lock:
            exact = self._exact.get((model, key))
            if exact is not None:
                self.hits += 1
                return exact
            entries = self._entries.get(model)
            if entries:
                query = _embed_hashed_ngrams(key, self.dim)
                vectors = np.stack([e[0] for e in entries])
                sims = vectors @ query
                best = int(np.argmax(sims))
                if float(sims[best]) >= self.threshold:
                    self.hits += 1
                    return entries[best][2]
            self.misses += 1
            return None

    def _insert(self, model: str, key: str, response: bytes,
                persist: bool = True) -> None:
        vec = _embed_hashed_ngrams(key, self.dim)
        entries = self._entries.setdefault(model, [])
        entries.append((vec, key, response))
        self._exact[(model, key)] = response
        if len(entries) > self.max_entries:
            _, old_key, _ = entries.pop(0)
            self._exact.pop((model, old_key), None)
        if persist:
            self._persist(model, key, response)

    def store(self, model: str, key: str, response: bytes) -> None:
        with self._lock:
            if (model, key) in self._exact:
                return
            self._insert(model, key, response)

    @property
    def size(self) -> int:
        return sum(len(v) for v in self._entries.values())

    def stats(self) -> Dict[str, float]:
        total = self.hits + self.misses
        return {
            "hits": self.hits,
            "misses": self.misses,
            "hit_ratio": self.hits / total if total else 0.0,
            "size": self.size,
        }
