"""PII detection for request bodies.

Reference counterpart: src/vllm_router/experimental/pii/ — PIIType taxonomy
(types.py:22-53), regex analyzer with five pattern families
(analyzers/regex.py:13-19), scan-and-block middleware with a block-on-error
policy (middleware.py:97-154) and its own Prometheus counters
(middleware.py:20-40).

Differences: the reference's second analyzer (Presidio NLP,
analyzers/presidio.py) needs model downloads the TPU image cannot assume, so
the factory's second analyzer here is dependency-free instead: ``secrets``
detects credential material (cloud API keys, tokens, private-key blocks,
mod-97-validated IBANs) — the PII class that matters most for a proxy that
logs and caches request bodies.  ``strict`` composes both.  Credit-card
matches are Luhn-validated to cut the false-positive rate of a bare digit
regex.
"""

from __future__ import annotations

import enum
import logging
import re
from typing import Any, Dict, Iterable, List, Set

from prometheus_client import Counter

logger = logging.getLogger(__name__)

pii_requests_scanned = Counter(
    "tpu_router:pii_requests_scanned",
    "Requests scanned by the PII middleware",
)
pii_requests_blocked = Counter(
    "tpu_router:pii_requests_blocked",
    "Requests blocked because PII was detected (or scanning failed)",
)
pii_detections = Counter(
    "tpu_router:pii_detections",
    "PII entities detected in request bodies",
    ["pii_type"],
)


class PIIType(enum.Enum):
    EMAIL = "email"
    PHONE_NUMBER = "phone_number"
    SSN = "ssn"
    CREDIT_CARD = "credit_card"
    IP_ADDRESS = "ip_address"
    IBAN = "iban"
    API_KEY = "api_key"
    PRIVATE_KEY = "private_key"
    # NER-detected entity classes (NERAnalyzer; reference
    # analyzers/presidio.py maps the same presidio entities).
    PERSON = "person"
    LOCATION = "location"
    ORGANIZATION = "organization"


class RegexAnalyzer:
    """Pattern-based analyzer (reference analyzers/regex.py:13-19)."""

    name = "regex"

    _PATTERNS: Dict[PIIType, re.Pattern] = {
        PIIType.EMAIL: re.compile(
            r"\b[a-zA-Z0-9._%+-]+@[a-zA-Z0-9.-]+\.[a-zA-Z]{2,}\b"
        ),
        # Separator-delimited US numbers; a bare 10-digit run is too noisy.
        PIIType.PHONE_NUMBER: re.compile(
            r"(?<!\d)(?:\+?1[-.\s])?\(?\d{3}\)?[-.\s]\d{3}[-.\s]\d{4}(?!\d)"
        ),
        PIIType.SSN: re.compile(r"(?<!\d)\d{3}-\d{2}-\d{4}(?!\d)"),
        PIIType.CREDIT_CARD: re.compile(r"(?<!\d)(?:\d[ -]?){12,18}\d(?!\d)"),
        PIIType.IP_ADDRESS: re.compile(
            r"(?<!\d)(?:(?:25[0-5]|2[0-4]\d|1?\d?\d)\.){3}"
            r"(?:25[0-5]|2[0-4]\d|1?\d?\d)(?!\d)"
        ),
    }

    def analyze(self, text: str) -> Set[PIIType]:
        found: Set[PIIType] = set()
        for pii_type, pattern in self._PATTERNS.items():
            for match in pattern.finditer(text):
                if pii_type is PIIType.CREDIT_CARD and not _luhn_ok(match.group()):
                    continue
                found.add(pii_type)
                break
        return found


def _luhn_ok(candidate: str) -> bool:
    digits = [int(c) for c in candidate if c.isdigit()]
    if not 13 <= len(digits) <= 19:
        return False
    checksum = 0
    for i, d in enumerate(reversed(digits)):
        if i % 2 == 1:
            d *= 2
            if d > 9:
                d -= 9
        checksum += d
    return checksum % 10 == 0


def _iban_ok(candidate: str) -> bool:
    """ISO 13616 mod-97 check (rearrange, letters -> 10..35, mod 97 == 1)."""
    s = candidate.replace(" ", "").upper()
    if not 15 <= len(s) <= 34:
        return False
    rearranged = s[4:] + s[:4]
    try:
        value = int("".join(
            str(int(c, 36)) for c in rearranged
        ))
    except ValueError:
        return False
    return value % 97 == 1


class SecretsAnalyzer:
    """Credential-material analyzer: the highest-stakes PII for a router
    that logs bodies and stores them in caches/batch files.  All patterns
    are structure-validated (prefix formats; IBAN mod-97) so prose never
    trips them."""

    name = "secrets"

    _PATTERNS: Dict[PIIType, re.Pattern] = {
        # Cloud/API credentials by issuer-fixed prefix: AWS access keys,
        # Google API keys, GitHub tokens, Slack tokens, OpenAI-style keys.
        PIIType.API_KEY: re.compile(
            r"\b(?:AKIA[0-9A-Z]{16}"
            r"|AIza[0-9A-Za-z_-]{35}"
            r"|gh[pousr]_[A-Za-z0-9]{36,}"
            r"|xox[baprs]-[A-Za-z0-9-]{10,}"
            r"|sk-[A-Za-z0-9_-]{20,})\b"
        ),
        PIIType.PRIVATE_KEY: re.compile(
            r"-----BEGIN (?:RSA |EC |DSA |OPENSSH |PGP )?PRIVATE KEY(?: BLOCK)?-----"
        ),
        PIIType.IBAN: re.compile(
            r"\b[A-Z]{2}\d{2}(?:[ ]?[A-Z0-9]{2,4}){3,8}\b"
        ),
    }

    def analyze(self, text: str) -> Set[PIIType]:
        found: Set[PIIType] = set()
        for pii_type, pattern in self._PATTERNS.items():
            for match in pattern.finditer(text):
                if pii_type is PIIType.IBAN and not _iban_ok(match.group()):
                    continue
                found.add(pii_type)
                break
        return found


class StrictAnalyzer:
    """Union of every registered leaf analyzer (reference factory's
    multi-analyzer role, analyzers/factory.py:20-55)."""

    name = "strict"

    def __init__(self):
        self._analyzers = [RegexAnalyzer(), SecretsAnalyzer()]

    def analyze(self, text: str) -> Set[PIIType]:
        found: Set[PIIType] = set()
        for analyzer in self._analyzers:
            found |= analyzer.analyze(text)
        return found


# Model-side entity labels -> PIIType.  Covers the two common NER label
# vocabularies: CoNLL (PER/LOC/ORG, with or without B-/I- prefixes, the
# `entity_group` keys of transformers' aggregation) and presidio's
# (PERSON/LOCATION/ORGANIZATION).
_NER_LABEL_MAP = {
    "PER": PIIType.PERSON,
    "PERSON": PIIType.PERSON,
    "LOC": PIIType.LOCATION,
    "LOCATION": PIIType.LOCATION,
    "GPE": PIIType.LOCATION,
    "ORG": PIIType.ORGANIZATION,
    "ORGANIZATION": PIIType.ORGANIZATION,
}


class NERAnalyzer:
    """NER-grade analyzer (reference analyzers/presidio.py, 172 LoC).

    Presidio itself is not an installable dependency here; the same
    capability comes from a ``transformers`` token-classification
    pipeline over a LOCAL model checkpoint (``model_path`` argument or
    ``PSTPU_PII_NER_MODEL`` env — e.g. a dslim/bert-base-NER download
    baked into the deployment image).  Like presidio — whose analyzer
    bundles pattern recognizers alongside the NLP engine — this composes
    the regex + secrets analyzers with the model, so "ner" is a strict
    superset of "strict".

    ``pipeline`` injection exists for tests and for callers that already
    hold a loaded pipeline (one model can back many router workers).
    """

    name = "ner"

    def __init__(self, pipeline=None, model_path: str = None,
                 score_threshold: float = 0.5):
        import os

        self.score_threshold = score_threshold
        self._pattern_analyzers = [RegexAnalyzer(), SecretsAnalyzer()]
        if pipeline is not None:
            self._pipeline = pipeline
            return
        model_path = model_path or os.environ.get("PSTPU_PII_NER_MODEL")
        if not model_path:
            raise RuntimeError(
                "PII analyzer 'ner' needs a token-classification model: "
                "set PSTPU_PII_NER_MODEL to a local checkpoint directory "
                "(e.g. a dslim/bert-base-NER download) or pass "
                "model_path=.  The 'strict' analyzer needs no model."
            )
        try:
            from transformers import pipeline as hf_pipeline
        except ImportError as e:  # pragma: no cover - transformers baked in
            raise RuntimeError(
                "PII analyzer 'ner' requires the 'transformers' package"
            ) from e
        self._pipeline = hf_pipeline(
            "token-classification", model=model_path,
            aggregation_strategy="simple",
        )

    def analyze(self, text: str) -> Set[PIIType]:
        found: Set[PIIType] = set()
        for analyzer in self._pattern_analyzers:
            found |= analyzer.analyze(text)
        try:
            entities = self._pipeline(text)
        except Exception:
            # Fail toward detection pressure, not silence: the middleware's
            # block-on-error policy handles hard failures; a soft model
            # error keeps the pattern findings.
            logger.exception("NER pipeline failed; pattern results only")
            return found
        for ent in entities or []:
            label = str(
                ent.get("entity_group") or ent.get("entity") or ""
            ).upper()
            label = label.split("-", 1)[-1]  # B-PER / I-PER -> PER
            score = float(ent.get("score", 1.0))
            pii_type = _NER_LABEL_MAP.get(label)
            if pii_type is not None and score >= self.score_threshold:
                found.add(pii_type)
        return found


_ANALYZERS = {
    RegexAnalyzer.name: RegexAnalyzer,
    SecretsAnalyzer.name: SecretsAnalyzer,
    StrictAnalyzer.name: StrictAnalyzer,
    NERAnalyzer.name: NERAnalyzer,
}


def create_analyzer(name: str):
    """Factory seam (reference analyzers/factory.py:20-55)."""
    try:
        return _ANALYZERS[name]()
    except KeyError:
        raise ValueError(
            f"Unknown PII analyzer {name!r}; available: {sorted(_ANALYZERS)}"
        ) from None


def extract_scannable_text(body: Dict[str, Any]) -> str:
    """Pull user-supplied text out of an OpenAI-style request body:
    chat ``messages[].content`` (string or content-part list), completion
    ``prompt``, and embeddings ``input`` (reference middleware.py:101-120)."""
    parts: List[str] = []

    def _add(value: Any) -> None:
        if isinstance(value, str):
            parts.append(value)
        elif isinstance(value, list):
            for item in value:
                if isinstance(item, str):
                    parts.append(item)
                elif isinstance(item, dict) and isinstance(item.get("text"), str):
                    parts.append(item["text"])

    messages = body.get("messages")
    if isinstance(messages, list):
        for message in messages:
            if isinstance(message, dict):
                _add(message.get("content"))
    _add(body.get("prompt"))
    _add(body.get("input"))
    return "\n".join(parts)


def scan_request_body(analyzer, body: Dict[str, Any]) -> Set[PIIType]:
    """Scan one request body; counts every scan and detection."""
    pii_requests_scanned.inc()
    text = extract_scannable_text(body)
    if not text:
        return set()
    detected = analyzer.analyze(text)
    for pii_type in detected:
        pii_detections.labels(pii_type=pii_type.value).inc()
    return detected


def format_types(detected: Iterable[PIIType]) -> List[str]:
    return sorted(t.value for t in detected)
