"""PII detection for request bodies.

Reference counterpart: src/vllm_router/experimental/pii/ — PIIType taxonomy
(types.py:22-53), regex analyzer with five pattern families
(analyzers/regex.py:13-19), scan-and-block middleware with a block-on-error
policy (middleware.py:97-154) and its own Prometheus counters
(middleware.py:20-40).

Differences: the reference's second analyzer (Presidio NLP) needs model
downloads the TPU image cannot assume, so the pluggable seam keeps only the
dependency-free regex analyzer; credit-card matches are Luhn-validated to cut
the false-positive rate of a bare digit regex.
"""

from __future__ import annotations

import enum
import logging
import re
from typing import Any, Dict, Iterable, List, Set

from prometheus_client import Counter

logger = logging.getLogger(__name__)

pii_requests_scanned = Counter(
    "tpu_router:pii_requests_scanned",
    "Requests scanned by the PII middleware",
)
pii_requests_blocked = Counter(
    "tpu_router:pii_requests_blocked",
    "Requests blocked because PII was detected (or scanning failed)",
)
pii_detections = Counter(
    "tpu_router:pii_detections",
    "PII entities detected in request bodies",
    ["pii_type"],
)


class PIIType(enum.Enum):
    EMAIL = "email"
    PHONE_NUMBER = "phone_number"
    SSN = "ssn"
    CREDIT_CARD = "credit_card"
    IP_ADDRESS = "ip_address"


class RegexAnalyzer:
    """Pattern-based analyzer (reference analyzers/regex.py:13-19)."""

    name = "regex"

    _PATTERNS: Dict[PIIType, re.Pattern] = {
        PIIType.EMAIL: re.compile(
            r"\b[a-zA-Z0-9._%+-]+@[a-zA-Z0-9.-]+\.[a-zA-Z]{2,}\b"
        ),
        # Separator-delimited US numbers; a bare 10-digit run is too noisy.
        PIIType.PHONE_NUMBER: re.compile(
            r"(?<!\d)(?:\+?1[-.\s])?\(?\d{3}\)?[-.\s]\d{3}[-.\s]\d{4}(?!\d)"
        ),
        PIIType.SSN: re.compile(r"(?<!\d)\d{3}-\d{2}-\d{4}(?!\d)"),
        PIIType.CREDIT_CARD: re.compile(r"(?<!\d)(?:\d[ -]?){12,18}\d(?!\d)"),
        PIIType.IP_ADDRESS: re.compile(
            r"(?<!\d)(?:(?:25[0-5]|2[0-4]\d|1?\d?\d)\.){3}"
            r"(?:25[0-5]|2[0-4]\d|1?\d?\d)(?!\d)"
        ),
    }

    def analyze(self, text: str) -> Set[PIIType]:
        found: Set[PIIType] = set()
        for pii_type, pattern in self._PATTERNS.items():
            for match in pattern.finditer(text):
                if pii_type is PIIType.CREDIT_CARD and not _luhn_ok(match.group()):
                    continue
                found.add(pii_type)
                break
        return found


def _luhn_ok(candidate: str) -> bool:
    digits = [int(c) for c in candidate if c.isdigit()]
    if not 13 <= len(digits) <= 19:
        return False
    checksum = 0
    for i, d in enumerate(reversed(digits)):
        if i % 2 == 1:
            d *= 2
            if d > 9:
                d -= 9
        checksum += d
    return checksum % 10 == 0


_ANALYZERS = {RegexAnalyzer.name: RegexAnalyzer}


def create_analyzer(name: str):
    """Factory seam (reference analyzers/factory.py:20-55)."""
    try:
        return _ANALYZERS[name]()
    except KeyError:
        raise ValueError(
            f"Unknown PII analyzer {name!r}; available: {sorted(_ANALYZERS)}"
        ) from None


def extract_scannable_text(body: Dict[str, Any]) -> str:
    """Pull user-supplied text out of an OpenAI-style request body:
    chat ``messages[].content`` (string or content-part list), completion
    ``prompt``, and embeddings ``input`` (reference middleware.py:101-120)."""
    parts: List[str] = []

    def _add(value: Any) -> None:
        if isinstance(value, str):
            parts.append(value)
        elif isinstance(value, list):
            for item in value:
                if isinstance(item, str):
                    parts.append(item)
                elif isinstance(item, dict) and isinstance(item.get("text"), str):
                    parts.append(item["text"])

    messages = body.get("messages")
    if isinstance(messages, list):
        for message in messages:
            if isinstance(message, dict):
                _add(message.get("content"))
    _add(body.get("prompt"))
    _add(body.get("input"))
    return "\n".join(parts)


def scan_request_body(analyzer, body: Dict[str, Any]) -> Set[PIIType]:
    """Scan one request body; counts every scan and detection."""
    pii_requests_scanned.inc()
    text = extract_scannable_text(body)
    if not text:
        return set()
    detected = analyzer.analyze(text)
    for pii_type in detected:
        pii_detections.labels(pii_type=pii_type.value).inc()
    return detected


def format_types(detected: Iterable[PIIType]) -> List[str]:
    return sorted(t.value for t in detected)
