"""Experimental tier: feature gates wiring semantic cache + PII detection
into the proxy path.

Reference counterparts: src/vllm_router/experimental/feature_gates.py:114-142
(gate init from flag+env), routers/main_router.py:44-51 (cache check
pre-route), services/request_service/request.py:113-117 (cache store
post-stream), experimental/pii/middleware.py:101-154 (PII scan-and-block).

The integration point is the ``proxy_hooks`` seam in
production_stack_tpu/router/routers/main_router.py: ``pre_route`` may
short-circuit with a response (cache hit, PII block) and
``post_response_hook`` supplies the background store callable the data path
invokes after a completed proxy.
"""

from __future__ import annotations

import json
import logging
from typing import Any, Dict, Optional

from aiohttp import web
from prometheus_client import Counter, Gauge

from production_stack_tpu.router.services.request_service.request import (
    _error_response,
)

from production_stack_tpu.router.experimental.feature_gates import (
    FEATURE_GATES,
    PII_DETECTION,
    SEMANTIC_CACHE,
    FeatureGates,
    initialize_feature_gates,
)
from production_stack_tpu.router.experimental.pii import (
    create_analyzer,
    format_types,
    pii_requests_blocked,
    scan_request_body,
)
from production_stack_tpu.router.experimental.semantic_cache import (
    SEMANTIC_CACHE_SERVICE,
    SemanticCache,
)

logger = logging.getLogger(__name__)

# Prometheus surface (reference semantic_cache_integration.py:25-44).
semantic_cache_hits = Counter(
    "tpu_router:semantic_cache_hits", "Semantic cache hits served"
)
semantic_cache_misses = Counter(
    "tpu_router:semantic_cache_misses", "Semantic cache lookups that missed"
)
semantic_cache_size = Gauge(
    "tpu_router:semantic_cache_size", "Entries resident in the semantic cache"
)

_CHAT_PATH = "/v1/chat/completions"
_CACHE_KEY = "semantic_cache_store_key"


class ExperimentalProxyHooks:
    """pre/post hooks installed as ``app['proxy_hooks']``."""

    def __init__(
        self,
        gates: FeatureGates,
        cache: Optional[SemanticCache],
        pii_analyzer=None,
    ):
        self.gates = gates
        self.cache = cache
        self.pii_analyzer = pii_analyzer

    async def _read_json(self, request: web.Request) -> Optional[Dict[str, Any]]:
        # aiohttp caches the raw body, so the data path's later read() is free.
        raw = await request.read()
        if not raw:
            return None
        try:
            body = json.loads(raw)
        except json.JSONDecodeError:
            return None
        return body if isinstance(body, dict) else None

    async def pre_route(
        self, request: web.Request, path: str
    ) -> Optional[web.StreamResponse]:
        body = await self._read_json(request)

        if self.pii_analyzer is not None:
            # Block-on-error policy (reference middleware.py:97-98): a scan
            # failure must fail closed, not wave the request through.
            try:
                detected = scan_request_body(self.pii_analyzer, body or {})
            except Exception:
                logger.exception("PII scan failed; blocking request")
                pii_requests_blocked.inc()
                return _error_response(
                    400, "PII scan failed; request blocked by policy"
                )
            if detected:
                pii_requests_blocked.inc()
                types = format_types(detected)
                logger.warning("Blocked request containing PII: %s", types)
                return _error_response(
                    400,
                    "Request blocked: detected PII in request content "
                    f"({', '.join(types)})",
                )

        if self.cache is not None and path == _CHAT_PATH and body is not None:
            if not body.get("stream"):
                model = body.get("model")
                key = SemanticCache.request_key(body)
                if model and key:
                    cached = self.cache.lookup(model, key)
                    semantic_cache_size.set(self.cache.size)
                    if cached is not None:
                        semantic_cache_hits.inc()
                        return web.Response(
                            body=cached,
                            content_type="application/json",
                            headers={"x-semantic-cache": "hit"},
                        )
                    semantic_cache_misses.inc()
                    # Stash the key so post_response_hook stores the answer.
                    request[_CACHE_KEY] = (model, key)
        return None

    def post_response_hook(self, request: web.Request, path: str):
        """Return the background store callable for this request, or None
        (reference request.py:113-117)."""
        if self.cache is None:
            return None
        store_key = request.get(_CACHE_KEY)
        if store_key is None:
            return None
        model, key = store_key
        cache = self.cache

        async def store(body_json: Dict[str, Any], response_bytes: bytes) -> None:
            # Only cache well-formed completed JSON completions; SSE bodies
            # and backend error payloads must not poison the cache.
            try:
                payload = json.loads(response_bytes)
            except (ValueError, UnicodeDecodeError):
                return
            # Belt-and-braces on top of the status==200 gate in
            # process_request: both OpenAI ({"error": ...}) and vLLM
            # ({"object": "error"}) error envelopes are uncacheable.
            if (
                not isinstance(payload, dict)
                or "error" in payload
                or payload.get("object") == "error"
            ):
                return
            cache.store(model, key, response_bytes)
            semantic_cache_size.set(cache.size)

        return store


def initialize_experimental(app: web.Application, registry, args) -> None:
    """Parse gates and install whatever they enable
    (reference app.py:140-194)."""
    gates = initialize_feature_gates(args.feature_gates)
    registry.set(FEATURE_GATES, gates)

    cache = None
    if gates.is_enabled(SEMANTIC_CACHE):
        if args.semantic_cache_model != "hash":
            raise ValueError(
                f"Unknown --semantic-cache-model {args.semantic_cache_model!r}; "
                "this build ships the dependency-free 'hash' embedding"
            )
        cache = SemanticCache(
            threshold=args.semantic_cache_threshold,
            cache_dir=args.semantic_cache_dir,
        )
        registry.set(SEMANTIC_CACHE_SERVICE, cache)
        logger.info(
            "Semantic cache enabled (threshold=%.3f, dir=%s)",
            args.semantic_cache_threshold,
            args.semantic_cache_dir,
        )

    analyzer = None
    if gates.is_enabled(PII_DETECTION):
        analyzer = create_analyzer(args.pii_analyzer)
        logger.info("PII detection enabled (analyzer=%s)", args.pii_analyzer)

    if cache is not None or analyzer is not None:
        app["proxy_hooks"] = ExperimentalProxyHooks(gates, cache, analyzer)
