"""K8s-style feature gates for the experimental tier.

Reference counterpart: src/vllm_router/experimental/feature_gates.py:50-142
(gate names :14-15, env parsing :114-142).  Differences: an explicit
FeatureGates object carried in the service registry instead of a singleton
metaclass (SURVEY.md section 7 "Hot-reconfig correctness"), and strict
parsing — a malformed gate string fails startup instead of being silently
dropped.
"""

from __future__ import annotations

import dataclasses
import enum
import logging
import os
from typing import Dict, Optional, Set

logger = logging.getLogger(__name__)

FEATURE_GATES = "feature_gates"

SEMANTIC_CACHE = "SemanticCache"
PII_DETECTION = "PIIDetection"

ENV_VAR = "PSTPU_FEATURE_GATES"


class FeatureStage(enum.Enum):
    ALPHA = "Alpha"
    BETA = "Beta"
    GA = "GA"


@dataclasses.dataclass(frozen=True)
class Feature:
    name: str
    description: str
    stage: FeatureStage
    default_enabled: bool = False


KNOWN_FEATURES: Dict[str, Feature] = {
    feature.name: feature
    for feature in [
        Feature(
            SEMANTIC_CACHE,
            "Similarity cache serving repeated chat completions without "
            "touching a backend",
            FeatureStage.ALPHA,
        ),
        Feature(
            PII_DETECTION,
            "Scan request bodies for PII and reject matches",
            FeatureStage.ALPHA,
        ),
    ]
}


class FeatureGates:
    def __init__(self):
        self._enabled: Set[str] = {
            f.name for f in KNOWN_FEATURES.values() if f.default_enabled
        }

    def enable(self, name: str) -> None:
        self._enabled.add(name)

    def disable(self, name: str) -> None:
        self._enabled.discard(name)

    def is_enabled(self, name: str) -> bool:
        return name in self._enabled

    def enabled_features(self) -> Set[str]:
        return set(self._enabled)

    def configure(self, gates: Dict[str, bool]) -> None:
        for name, on in gates.items():
            if on:
                self.enable(name)
            else:
                self.disable(name)


def parse_gates(spec: str) -> Dict[str, bool]:
    """Parse ``Feature=true,Other=false``; unknown names or malformed
    entries raise (the reference logs-and-continues, which hides typos)."""
    gates: Dict[str, bool] = {}
    for item in spec.split(","):
        item = item.strip()
        if not item:
            continue
        if "=" not in item:
            raise ValueError(
                f"Malformed feature gate {item!r} (expected Name=true|false)"
            )
        name, _, value = item.partition("=")
        name = name.strip()
        value = value.strip().lower()
        if name not in KNOWN_FEATURES:
            raise ValueError(
                f"Unknown feature gate {name!r}; known: {sorted(KNOWN_FEATURES)}"
            )
        if value not in ("true", "false"):
            raise ValueError(
                f"Feature gate {name} has non-boolean value {value!r}"
            )
        gates[name] = value == "true"
    return gates


def initialize_feature_gates(spec: Optional[str] = None) -> FeatureGates:
    """Build gates from the env var then the CLI spec (CLI wins)."""
    gates = FeatureGates()
    env_spec = os.environ.get(ENV_VAR)
    if env_spec:
        gates.configure(parse_gates(env_spec))
    if spec:
        gates.configure(parse_gates(spec))
    enabled = sorted(gates.enabled_features())
    if enabled:
        logger.info("Enabled experimental features: %s", ", ".join(enabled))
    else:
        logger.info("No experimental features enabled")
    return gates
