"""OpenAI Files API endpoints.

Reference counterpart: src/vllm_router/routers/files_router.py:10-68.
Additions over the reference: GET /v1/files (list) and DELETE
/v1/files/{file_id} — both part of the OpenAI surface, declared by the
reference's Storage ABC but never wired to routes.
"""

from __future__ import annotations

from aiohttp import web

from production_stack_tpu.router.services.files_service import FILE_STORAGE

routes = web.RouteTableDef()


def _storage(request: web.Request):
    storage = request.app["registry"].get(FILE_STORAGE)
    if storage is None:
        raise web.HTTPServiceUnavailable(
            text='{"error": "file storage not initialized (--enable-batch-api)"}',
            content_type="application/json",
        )
    return storage


@routes.post("/v1/files")
async def upload_file(request: web.Request) -> web.Response:
    """Multipart upload with `file` + `purpose` fields
    (reference files_router.py:11-42)."""
    form = await request.post()
    if "file" not in form:
        return web.json_response(
            {"error": "Missing required parameter 'file'"}, status=400
        )
    field = form["file"]
    if not isinstance(field, web.FileField):
        return web.json_response(
            {"error": "'file' must be a file upload"}, status=400
        )
    purpose = str(form.get("purpose", "unknown"))
    content = field.file.read()
    try:
        info = await _storage(request).save_file(
            file_name=field.filename, content=content, purpose=purpose
        )
    except ValueError as e:
        return web.json_response({"error": str(e)}, status=400)
    return web.json_response(info.metadata())


@routes.get("/v1/files")
async def list_files(request: web.Request) -> web.Response:
    files = await _storage(request).list_files()
    return web.json_response(
        {"object": "list", "data": [f.metadata() for f in files]}
    )


@routes.get("/v1/files/{file_id}")
async def get_file(request: web.Request) -> web.Response:
    file_id = request.match_info["file_id"]
    try:
        info = await _storage(request).get_file(file_id)
    except FileNotFoundError:
        return web.json_response(
            {"error": f"File {file_id} not found"}, status=404
        )
    return web.json_response(info.metadata())


@routes.get("/v1/files/{file_id}/content")
async def get_file_content(request: web.Request) -> web.Response:
    file_id = request.match_info["file_id"]
    try:
        content = await _storage(request).get_file_content(file_id)
    except FileNotFoundError:
        return web.json_response(
            {"error": f"File {file_id} not found"}, status=404
        )
    return web.Response(body=content, content_type="application/octet-stream")


@routes.delete("/v1/files/{file_id}")
async def delete_file(request: web.Request) -> web.Response:
    file_id = request.match_info["file_id"]
    try:
        await _storage(request).delete_file(file_id)
    except FileNotFoundError:
        return web.json_response(
            {"error": f"File {file_id} not found"}, status=404
        )
    return web.json_response({"id": file_id, "object": "file", "deleted": True})
