"""OpenAI Batch API endpoints.

Reference counterpart: src/vllm_router/routers/batches_router.py:10-100.
Cancellation is exposed both as DELETE /v1/batches/{id} (the reference's
route) and POST /v1/batches/{id}/cancel (the actual OpenAI route).
"""

from __future__ import annotations

from aiohttp import web

from production_stack_tpu.router.services.batch_service import BATCH_PROCESSOR
from production_stack_tpu.router.services.files_service import FILE_STORAGE

routes = web.RouteTableDef()


def _processor(request: web.Request):
    processor = request.app["registry"].get(BATCH_PROCESSOR)
    if processor is None:
        raise web.HTTPServiceUnavailable(
            text='{"error": "batch processor not initialized (--enable-batch-api)"}',
            content_type="application/json",
        )
    return processor


@routes.post("/v1/batches")
async def create_batch(request: web.Request) -> web.Response:
    try:
        body = await request.json()
    except Exception:
        return web.json_response({"error": "invalid JSON body"}, status=400)
    for field in ("input_file_id", "endpoint"):
        if field not in body:
            return web.json_response(
                {"error": f"Missing required parameter '{field}'"}, status=400
            )
    file_id = body["input_file_id"]
    storage = request.app["registry"].get(FILE_STORAGE)
    try:
        await storage.get_file(file_id)
    except FileNotFoundError:
        return web.json_response(
            {"error": f"File {file_id} not found"}, status=404
        )
    try:
        info = await _processor(request).create_batch(
            input_file_id=file_id,
            endpoint=body["endpoint"],
            completion_window=body.get("completion_window", "24h"),
            metadata=body.get("metadata"),
        )
    except ValueError as e:
        return web.json_response({"error": str(e)}, status=400)
    return web.json_response(info.to_dict())


@routes.get("/v1/batches")
async def list_batches(request: web.Request) -> web.Response:
    try:
        limit = int(request.query.get("limit", "20"))
    except ValueError:
        return web.json_response({"error": "limit must be an integer"}, status=400)
    # OpenAI clamps to 1..100; also keeps SQLite's LIMIT -1 (= unlimited)
    # and the has_more=true-on-empty-page degenerate cases out.
    limit = max(1, min(limit, 100))
    after = request.query.get("after")
    batches = await _processor(request).list_batches(limit=limit, after=after)
    data = [b.to_dict() for b in batches]
    return web.json_response({
        "object": "list",
        "data": data,
        "first_id": data[0]["id"] if data else None,
        "last_id": data[-1]["id"] if data else None,
        "has_more": len(data) == limit,
    })


@routes.get("/v1/batches/{batch_id}")
async def get_batch(request: web.Request) -> web.Response:
    batch_id = request.match_info["batch_id"]
    try:
        info = await _processor(request).retrieve_batch(batch_id)
    except FileNotFoundError:
        return web.json_response(
            {"error": f"Batch {batch_id} not found"}, status=404
        )
    return web.json_response(info.to_dict())


async def _cancel(request: web.Request) -> web.Response:
    batch_id = request.match_info["batch_id"]
    try:
        info = await _processor(request).cancel_batch(batch_id)
    except FileNotFoundError:
        return web.json_response(
            {"error": f"Batch {batch_id} not found"}, status=404
        )
    return web.json_response(info.to_dict())


routes.delete("/v1/batches/{batch_id}")(_cancel)
routes.post("/v1/batches/{batch_id}/cancel")(_cancel)
