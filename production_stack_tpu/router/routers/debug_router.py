"""Router /debug/requests: completed request timelines + router/engine join.

``GET /debug/requests`` lists the router's completed timelines (bounded
ring, newest first).  ``GET /debug/requests/{request_id}`` joins the
router's timeline with the serving engine's (fetched live from the backend
that handled the request) into one span list, and scores the
non-overlapping phase set against wall-clock e2e — the "where did the time
go" answer for a slow request.
"""

from __future__ import annotations

import logging
from typing import Dict, Optional

import aiohttp
from aiohttp import web

from production_stack_tpu.obs.engine import PHASE_SPAN_NAMES
from production_stack_tpu.router.services.request_service.request import (
    CLIENT_SESSION,
    ROUTER_TRACER,
)

logger = logging.getLogger(__name__)

routes = web.RouteTableDef()

# How long the join waits on the engine's debug endpoint; a slow/gone
# engine degrades to a router-only timeline, never a hung debug request.
_ENGINE_FETCH_TIMEOUT_S = 2.0


@routes.get("/debug/requests")
async def list_requests(request: web.Request) -> web.Response:
    tracer = request.app["registry"].get(ROUTER_TRACER)
    if tracer is None or not tracer.enabled:
        return web.json_response({"enabled": False, "requests": []})
    return web.json_response({
        "enabled": True,
        "requests": tracer.snapshots(),
    })


async def _fetch_engine_trace(
    session: aiohttp.ClientSession, server: str, request_id: str
) -> Optional[Dict]:
    try:
        async with session.get(
            f"{server}/debug/requests/{request_id}",
            timeout=aiohttp.ClientTimeout(total=_ENGINE_FETCH_TIMEOUT_S),
        ) as resp:
            if resp.status != 200:
                return None
            return await resp.json()
    except Exception:
        logger.debug("engine trace fetch failed for %s", request_id,
                     exc_info=True)
        return None


def join_timelines(router_trace: Dict, engine_trace: Optional[Dict]) -> Dict:
    """Merge router + engine span lists into one timeline and attribute
    the request's wall-clock to the non-overlapping phase set
    (PHASE_SPAN_NAMES).  Pure function — unit-testable without servers."""
    spans = list(router_trace.get("spans", []))
    if engine_trace is not None:
        spans.extend(engine_trace.get("spans", []))
    spans.sort(key=lambda s: s.get("start", 0.0))
    phase_s = {
        s["name"]: round(s.get("duration_s", 0.0), 6)
        for s in spans
        if s["name"] in PHASE_SPAN_NAMES
    }
    total_s = router_trace.get("duration_s", 0.0)
    joined = {
        "request_id": router_trace.get("request_id"),
        "trace_id": router_trace.get("trace_id"),
        "router": router_trace,
        "engine": engine_trace,
        "spans": spans,
        "phase_s": phase_s,
        "phase_sum_s": round(sum(phase_s.values()), 6),
        "total_s": round(total_s, 6),
    }
    if engine_trace is not None and engine_trace.get("windows") is not None:
        # The engine's window flight records ride the join inline: which
        # dispatches this request's tokens rode, what else shared them,
        # and which one stalled (obs/flight_recorder.py).
        joined["windows"] = engine_trace["windows"]
    return joined


@routes.get("/debug/requests/{request_id}")
async def get_request(request: web.Request) -> web.Response:
    registry = request.app["registry"]
    tracer = registry.get(ROUTER_TRACER)
    if tracer is None or not tracer.enabled:
        return web.json_response(
            {"error": {"message": "tracing is disabled (--no-tracing)"}},
            status=404,
        )
    request_id = request.match_info["request_id"]
    router_trace = tracer.snapshot(request_id)
    if router_trace is None:
        return web.json_response(
            {"error": {"message": "unknown request id (expired from the "
                       "trace ring?)"}},
            status=404,
        )
    engine_trace = None
    server = router_trace["attrs"].get("server")
    session = registry.get(CLIENT_SESSION)
    if server and session is not None:
        engine_trace = await _fetch_engine_trace(session, server, request_id)
    return web.json_response(join_timelines(router_trace, engine_trace))
