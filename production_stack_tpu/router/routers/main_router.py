"""OpenAI-compatible endpoints + /v1/models + /health + /version.

Reference counterpart: src/vllm_router/routers/main_router.py:42-160.
"""

from __future__ import annotations

import time

from aiohttp import web

from production_stack_tpu.router.service_discovery import DISCOVERY_SERVICE
from production_stack_tpu.router.services.request_service import route_general_request
from production_stack_tpu.router.services.request_service.request import (
    ENGINE_STATS_SCRAPER,
)
from production_stack_tpu.utils.drain import DRAIN_CONTROLLER
from production_stack_tpu.version import __version__

routes = web.RouteTableDef()

# Proxied OpenAI endpoints (reference main_router.py:42-91).  Each handler
# binds the upstream path explicitly so aliases (/rerank, /score) work.
_PROXY_PATHS = [
    "/v1/chat/completions",
    "/v1/completions",
    "/v1/embeddings",
    "/v1/rerank",
    "/rerank",
    "/v1/score",
    "/score",
    # Engine utility endpoints (vLLM parity): tokenization follows the
    # model, so these route like any model-bound request.
    "/tokenize",
    "/detokenize",
]


def _make_proxy_handler(path: str):
    async def handler(request: web.Request) -> web.StreamResponse:
        hooks = request.app.get("proxy_hooks")
        if hooks is not None:
            short_circuit = await hooks.pre_route(request, path)
            if short_circuit is not None:
                return short_circuit
            return await route_general_request(
                request, path, background=hooks.post_response_hook(request, path)
            )
        return await route_general_request(request, path)

    return handler


for _path in _PROXY_PATHS:
    routes.post(_path)(_make_proxy_handler(_path))


@routes.get("/v1/models")
async def show_models(request: web.Request) -> web.Response:
    """Aggregate model cards across discovered endpoints
    (reference main_router.py:93-122)."""
    registry = request.app["registry"]
    discovery = registry.require(DISCOVERY_SERVICE)
    seen = {}
    for ep in discovery.get_endpoint_info():
        for name in ep.model_names:
            if name not in seen:
                seen[name] = {
                    "id": name,
                    "object": "model",
                    "created": int(ep.added_timestamp),
                    "owned_by": "production-stack-tpu",
                }
    return web.json_response({"object": "list", "data": list(seen.values())})


@routes.get("/version")
async def show_version(request: web.Request) -> web.Response:
    return web.json_response({"version": __version__})


@routes.get("/ready")
async def ready(request: web.Request) -> web.Response:
    """Readiness: liveness checks PLUS the drain state — a draining
    router must leave its Service endpoints (so the LB stops sending new
    work) while /health keeps passing (kubelet must not kill it
    mid-stream).  docs/robustness.md "Drain sequence"."""
    registry = request.app["registry"]
    drain = registry.get(DRAIN_CONTROLLER)
    if drain is not None and drain.draining:
        return web.json_response(
            {"status": "draining", "in_flight": drain.in_flight}, status=503
        )
    return await health(request)


@routes.post("/drain")
async def drain_endpoint(request: web.Request) -> web.Response:
    """Flip readiness, reject new data-plane work, finish in-flight
    streams within the grace, then exit (helm preStop hook; SIGTERM lands
    on the same controller)."""
    registry = request.app["registry"]
    drain = registry.get(DRAIN_CONTROLLER)
    if drain is None:
        return web.json_response(
            {"error": {"message": "drain controller not initialized"}},
            status=501,
        )
    drain.begin()
    return web.json_response({
        "draining": True,
        "in_flight": drain.in_flight,
        "grace_s": drain.grace_s,
    })


@routes.get("/health")
async def health(request: web.Request) -> web.Response:
    """Composite liveness: discovery + stats scraper
    (reference main_router.py:125-160)."""
    registry = request.app["registry"]
    problems = []
    discovery = registry.get(DISCOVERY_SERVICE)
    if discovery is None:
        problems.append("service discovery not initialized")
    elif not discovery.get_health():
        problems.append("service discovery watcher is down")
    scraper = registry.get(ENGINE_STATS_SCRAPER)
    if scraper is not None and not scraper.get_health():
        problems.append("engine stats scraper is down")
    dynamic_config = registry.get("dynamic_config_watcher")
    if dynamic_config is not None and not dynamic_config.get_health():
        problems.append("dynamic config watcher is down")
    if problems:
        return web.json_response({"status": "unhealthy", "problems": problems}, status=503)
    body = {"status": "healthy", "time": time.time()}
    if dynamic_config is not None:
        body["dynamic_config"] = dynamic_config.current_config_digest()
    return web.json_response(body)
