"""aiohttp route tables (reference counterpart: src/vllm_router/routers/)."""
