"""Router /metrics endpoint.

Reference counterpart: src/vllm_router/routers/metrics_router.py:24-64 —
refreshes labeled gauges from the request-stats monitor and discovery on
every scrape, then renders the default registry.
"""

from __future__ import annotations

import time

from aiohttp import web
from prometheus_client import CONTENT_TYPE_LATEST, generate_latest

from production_stack_tpu.obs.histogram import render_labeled_histograms
from production_stack_tpu.router.capacity import CAPACITY_MODEL
from production_stack_tpu.router.routing import ROUTING_SERVICE
from production_stack_tpu.router.service_discovery import (
    DISCOVERY_SERVICE,
    decode_capable,
    encode_capable,
    role_pool,
    roles_configured,
)
from production_stack_tpu.router.services import metrics_service as ms
from production_stack_tpu.router.services.request_service.request import (
    CIRCUIT_BREAKER,
    ENGINE_STATS_SCRAPER,
    REQUEST_STATS_MONITOR,
    ROUTER_TRACER,
)
from production_stack_tpu.router.stats.vocabulary import ROUTER_HISTOGRAMS

routes = web.RouteTableDef()


def render_router_histograms(monitor) -> str:
    """Per-server latency histogram families (TTFT/ITL/e2e/queueing) —
    the p50/p95/p99 counterpart of the averages above, appended after the
    prometheus_client body.  Families render for every server the monitor
    has seen, zero-observation instances included, so scrape names are
    stable."""
    by_server = monitor.get_histograms()
    parts = []
    for key, family_name in ROUTER_HISTOGRAMS.items():
        per_server = {
            server: hists[key] for server, hists in by_server.items()
        }
        # A family header with no instances is legal exposition; emitting
        # it on an idle router keeps the names present from the first
        # scrape, so alert rules can tell "no traffic yet" from "metric
        # renamed/broken".
        parts.append(
            render_labeled_histograms(family_name, per_server, "server")
        )
    return "".join(parts)


@routes.get("/metrics")
async def metrics(request: web.Request) -> web.Response:
    registry = request.app["registry"]
    discovery = registry.get(DISCOVERY_SERVICE)
    scraper = registry.get(ENGINE_STATS_SCRAPER)
    engine_stats = scraper.get_engine_stats() if scraper is not None else {}

    monitor = registry.get(REQUEST_STATS_MONITOR)
    request_stats = {}
    if monitor is not None:
        # One snapshot serves the gauge refresh AND the capacity model;
        # quantiles on — the model's SLO clamp reads itl_p95/ttft_p95,
        # and a scrape is the rate-limited place to pay the sort.
        request_stats = monitor.get_request_stats(
            time.time(), with_quantiles=True
        )
        for server, stats in request_stats.items():
            ms.current_qps.labels(server=server).set(stats.qps)
            ms.avg_ttft.labels(server=server).set(stats.ttft)
            ms.avg_latency.labels(server=server).set(stats.latency)
            ms.avg_itl.labels(server=server).set(stats.itl)
            ms.avg_decoding_length.labels(server=server).set(stats.decoding_length)
            ms.queueing_delay.labels(server=server).set(stats.queueing_delay)
            ms.num_prefill_requests.labels(server=server).set(stats.in_prefill_requests)
            ms.num_decoding_requests.labels(server=server).set(stats.in_decoding_requests)
            ms.num_requests_finished.labels(server=server).set(stats.finished_requests)
            ms.num_requests_uncompleted.labels(server=server).set(
                stats.uncompleted_requests
            )
            # Compile-excluded windowed TTFT p95 (the raw windowed p95
            # feeds the capacity model; this one is the dashboard's
            # steady-state line — the gap between the two IS the XLA
            # compile cost the engine's first-chunk marker attributed).
            ms.ttft_clean_p95.labels(server=server).set(stats.ttft_clean_p95)

    # Router trace-ring evictions: the tracer counts cumulatively, the
    # prometheus Counter wants increments — inc the delta at scrape time
    # (same single-scraper assumption the engine-side counters make).
    tracer = registry.get(ROUTER_TRACER)
    if tracer is not None:
        dropped = tracer.dropped
        seen = request.app.get("_obs_dropped_seen", 0)
        if dropped > seen:
            ms.obs_trace_dropped_total.inc(dropped - seen)
            request.app["_obs_dropped_seen"] = dropped

    breaker = registry.get(CIRCUIT_BREAKER)
    if breaker is not None:
        if discovery is not None:
            # Retire breaker state + gauge labels for backends that left
            # discovery (pod churn would otherwise grow both unboundedly).
            live = [ep.url for ep in discovery.get_endpoint_info()]
            for gone in breaker.prune(live):
                try:
                    ms.circuit_state.remove(gone)
                except KeyError:
                    pass
        for server, state_value in breaker.snapshot().items():
            ms.circuit_state.labels(server=server).set(state_value)

    for server, es in engine_stats.items():
        ms.engine_kv_usage_perc.labels(server=server).set(es.kv_usage_perc)
        ms.engine_prefix_cache_hit_rate.labels(server=server).set(
            es.prefix_cache_hit_rate
        )
        ms.engine_queue_depth.labels(server=server).set(es.num_queuing_requests)

    # Fleet-wide KV hit rate from the engines' scraped truth counters
    # (token-weighted — the BASELINE.md north-star metric, one scrape
    # point for the whole fleet).
    total_hit = sum(es.prefix_cache_hit_tokens for es in engine_stats.values())
    total_query = sum(
        es.prefix_cache_query_tokens for es in engine_stats.values()
    )
    ms.fleet_prefix_hit_rate.set(total_hit / total_query if total_query else 0.0)

    # Prefix-popularity view (routing logic kv_aware_popularity): retire
    # owner-map/replica-set state for departed backends (pod churn, the
    # CapacityModel.prune contract) and export the replication degree.
    routing = registry.get(ROUTING_SERVICE)
    if routing is not None and discovery is not None and hasattr(routing, "prune"):
        routing.prune([ep.url for ep in discovery.get_endpoint_info()])
    if routing is not None and hasattr(routing, "popularity_snapshot"):
        snap = routing.popularity_snapshot()
        ms.prefix_replica_set_size.set(snap["replica_set_max"])

    # Fleet capacity model (router/capacity.py): refresh from the live
    # stats plane so a scrape always reflects current headroom, then
    # export per-pool headroom and per-backend capacity/score.
    capacity = registry.get(CAPACITY_MODEL)
    if capacity is not None and discovery is not None:
        all_endpoints = discovery.get_endpoint_info()
        # Admission pools exclude sleeping endpoints; pruning must NOT —
        # a backend asleep is still in discovery, and evicting its
        # learned capacity would restart it at the optimistic prior on
        # wake (prune is for pod churn only).
        endpoints = [ep for ep in all_endpoints if not ep.sleep]
        capacity.refresh(endpoints, engine_stats, request_stats, prune=False)
        gone_urls = capacity.prune([ep.url for ep in all_endpoints])
        ms.fleet_headroom_slots.labels(pool="fleet").set(
            capacity.pool_headroom(endpoints, request_stats)
        )
        if roles_configured(endpoints):
            ms.fleet_headroom_slots.labels(pool="prefill").set(
                capacity.pool_headroom(
                    role_pool(endpoints, "prefill"), request_stats
                )
            )
            ms.fleet_headroom_slots.labels(pool="decode").set(
                capacity.pool_headroom(decode_capable(endpoints), request_stats)
            )
            # Encode lane isolation is observable: the pool an embed
            # burst sheds against (dedicated encode members + fused
            # backends), separate from the generation pools above.
            ms.fleet_headroom_slots.labels(pool="encode").set(
                capacity.pool_headroom(encode_capable(endpoints), request_stats)
            )
        else:
            # Roles gone (fleet hot-swapped back to fused): retire the
            # per-role labels instead of freezing their last values — a
            # frozen headroom=0 series would pin the adapter's
            # min()-over-pools HPA signal at zero forever.
            for stale_pool in ("prefill", "decode", "encode"):
                try:
                    ms.fleet_headroom_slots.remove(stale_pool)
                except KeyError:
                    pass
        for server, bc in capacity.snapshot().items():
            ms.backend_capacity_slots.labels(server=server).set(bc.slots)
            ms.backend_capacity_score.labels(server=server).set(
                capacity.capacity_score(server)
            )
        # Retire labels for departed backends (pod churn) — same contract
        # as circuit_state above.
        for gone in gone_urls:
            for gauge in (ms.backend_capacity_slots, ms.backend_capacity_score):
                try:
                    gauge.remove(gone)
                except KeyError:
                    pass

    if discovery is not None:
        per_model: dict = {}
        for ep in discovery.get_endpoint_info():
            for model in ep.model_names or ["<unknown>"]:
                per_model[model] = per_model.get(model, 0) + 1
        for model, count in per_model.items():
            ms.healthy_pods_total.labels(model=model).set(count)

    body = generate_latest()
    if monitor is not None:
        body += render_router_histograms(monitor).encode()
    return web.Response(body=body, headers={"Content-Type": CONTENT_TYPE_LATEST})
