"""Hot-reload of router config from a watched JSON file.

Reference counterpart: src/vllm_router/dynamic_config.py:20-209
(DynamicRouterConfig :20-76, DynamicConfigWatcher :79-209).  The file is
written by the operator's ConfigMap pipeline (native/operator; reference
staticroute_controller.go:134-184) and projected into the router pod.

Differences from the reference:

* asyncio task instead of a polling thread.
* Reconfiguration swaps services in the ServiceRegistry and re-points the
  stats scraper — no singleton-registry purge (the reference tears down
  metaclass singletons in place, routing_logic.py:189-196, a documented
  hot-reconfig race in SURVEY.md section 7).
* The watcher also tracks the file's mtime so an unchanged config costs a
  stat(), not a parse.
"""

from __future__ import annotations

import argparse
import asyncio
import dataclasses
import hashlib
import json
import logging
import os
from typing import Optional

from production_stack_tpu.router.routing import reconfigure_routing_logic
from production_stack_tpu.router.service_discovery import (
    DISCOVERY_SERVICE,
    build_service_discovery,
)
from production_stack_tpu.router.services.request_service.request import (
    ENGINE_STATS_SCRAPER,
)

logger = logging.getLogger(__name__)

DYNAMIC_CONFIG_WATCHER = "dynamic_config_watcher"


@dataclasses.dataclass
class DynamicRouterConfig:
    """Hot-reconfigurable subset of the router CLI surface
    (reference dynamic_config.py:20-76)."""

    service_discovery: str
    routing_logic: str
    static_backends: Optional[str] = None
    static_models: Optional[str] = None
    k8s_port: Optional[int] = None
    k8s_namespace: Optional[str] = None
    k8s_label_selector: Optional[str] = None
    session_key: Optional[str] = None

    @staticmethod
    def from_json(path: str) -> "DynamicRouterConfig":
        with open(path) as f:
            data = json.load(f)
        known = {f.name for f in dataclasses.fields(DynamicRouterConfig)}
        unknown = set(data) - known
        if unknown:
            logger.warning("dynamic config: ignoring unknown keys %s", sorted(unknown))
        return DynamicRouterConfig(**{k: v for k, v in data.items() if k in known})

    @staticmethod
    def from_args(args) -> "DynamicRouterConfig":
        return DynamicRouterConfig(
            service_discovery=args.service_discovery,
            routing_logic=args.routing_logic,
            static_backends=args.static_backends,
            static_models=args.static_models,
            k8s_port=args.k8s_port,
            k8s_namespace=args.k8s_namespace,
            k8s_label_selector=args.k8s_label_selector,
            session_key=args.session_key,
        )


class DynamicConfigWatcher:
    """Polls the JSON file; on change rebuilds discovery + routing in the
    registry (reference _watch_worker, dynamic_config.py:180-201)."""

    def __init__(self, config_json: str, registry, args, watch_interval: float = 10.0):
        self.config_json = config_json
        self.registry = registry
        self.args = args
        self.watch_interval = watch_interval
        self.current_config = DynamicRouterConfig.from_args(args)
        self.reconfig_count = 0
        self._mtime: Optional[float] = None
        self._task: Optional[asyncio.Task] = None

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> None:
        # Apply immediately if the file already exists (the operator may
        # have written it before the router started).
        await self._check_once()
        self._task = asyncio.create_task(self._run())

    async def close(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    def get_health(self) -> bool:
        return self._task is not None and not self._task.done()

    def get_current_config(self) -> DynamicRouterConfig:
        return self.current_config

    def current_config_digest(self) -> str:
        """Short stable digest surfaced in /health so operators (and the
        native operator's health poll) can confirm which config is live."""
        blob = json.dumps(dataclasses.asdict(self.current_config), sort_keys=True)
        return hashlib.sha256(blob.encode()).hexdigest()[:12]

    # -- watch loop --------------------------------------------------------

    async def _run(self) -> None:
        while True:
            await asyncio.sleep(self.watch_interval)
            await self._check_once()

    async def _check_once(self) -> None:
        try:
            mtime = os.stat(self.config_json).st_mtime
        except OSError:
            return  # file not written yet
        if mtime == self._mtime:
            return
        try:
            config = DynamicRouterConfig.from_json(self.config_json)
        except (json.JSONDecodeError, TypeError, OSError) as e:
            # Leave _mtime stale: the next poll retries (and keeps warning)
            # until the operator writes a loadable file.
            logger.warning("dynamic config: failed to load %s: %s", self.config_json, e)
            return
        if config == self.current_config:
            self._mtime = mtime
            return
        logger.info("dynamic config changed; reconfiguring router")
        try:
            await self._reconfigure(config)
        except Exception:
            # Transient failure (e.g. K8s API unreachable): keep _mtime
            # stale so the next poll retries the same file.
            logger.exception("dynamic config: reconfiguration failed")
            return
        self._mtime = mtime
        self.current_config = config
        self.reconfig_count += 1
        logger.info("dynamic config: reconfiguration complete")

    # -- reconfiguration ---------------------------------------------------

    async def _reconfigure(self, config: DynamicRouterConfig) -> None:
        await self._reconfigure_discovery(config)
        self._reconfigure_routing(config)

    def _merged_args(self, config: DynamicRouterConfig) -> argparse.Namespace:
        """Overlay the dynamic config onto the startup args so the shared
        builder keeps everything the dynamic surface does not cover
        (model labels/types, probing, ...)."""
        merged = argparse.Namespace(**vars(self.args))
        for field in dataclasses.fields(config):
            value = getattr(config, field.name)
            if value is not None:
                setattr(merged, field.name, value)
        return merged

    async def _reconfigure_discovery(self, config: DynamicRouterConfig) -> None:
        new = build_service_discovery(self._merged_args(config))
        await new.start()
        old = self.registry.get(DISCOVERY_SERVICE)
        self.registry.replace(DISCOVERY_SERVICE, lambda: new)
        # The scraper holds a direct reference; re-point it at the new
        # discovery so the next scrape cycle sees the new endpoint set.
        scraper = self.registry.get(ENGINE_STATS_SCRAPER)
        if scraper is not None:
            scraper.service_discovery = new
        if old is not None:
            await old.close()

    def _reconfigure_routing(self, config: DynamicRouterConfig) -> None:
        # Same flag->kwargs mapping boot uses, so a hot-reload keeps the
        # CLI-tuned kv-affinity/popularity knobs instead of silently
        # rebuilding the router from library defaults.
        from production_stack_tpu.router.app import routing_kwargs_from_args

        kwargs = routing_kwargs_from_args(config.routing_logic, self.args)
        if config.routing_logic == "session":
            kwargs["session_key"] = config.session_key or self.args.session_key
        reconfigure_routing_logic(self.registry, config.routing_logic, **kwargs)
