"""Kubernetes pod-watch service discovery (asyncio, raw K8s REST API).

Reference counterpart: src/vllm_router/service_discovery.py:85-267
(K8sServiceDiscovery: watch loop :157-182, readiness gating :120-129,
model probe :131-155, add/delete :184-239).

Differences from the reference:

* Raw HTTPS against the API server (aiohttp) instead of the ``kubernetes``
  client package — the heavyweight client is not a given on TPU images,
  and the watch protocol is just line-delimited JSON.
* asyncio task on the router's event loop instead of a daemon thread with
  a lock-guarded dict (single-threaded mutation, no locks).
* List-then-watch with resourceVersion bookkeeping and 410-Gone recovery
  (the reference's 30 s watch timeout re-lists implicitly every cycle).
* Probes every model id on the pod (multi-model engines), not data[0].

In-cluster credentials come from the standard service-account mount; the
constructor accepts explicit ``api_server/token/ca_path`` for tests
(tests/test_k8s_discovery.py runs a fake API server).
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import ssl
import time
from typing import Dict, List, Optional

import aiohttp

from production_stack_tpu.router.service_discovery import (
    DEFAULT_ROLE_LABEL,
    ENGINE_ROLES,
    EndpointInfo,
    ServiceDiscovery,
)

logger = logging.getLogger(__name__)

SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


def in_cluster_api_server() -> str:
    host = os.environ.get("KUBERNETES_SERVICE_HOST", "kubernetes.default.svc")
    port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
    return f"https://{host}:{port}"


class K8sServiceDiscovery(ServiceDiscovery):
    def __init__(
        self,
        namespace: str = "default",
        port: int = 8000,
        label_selector: str = "",
        api_server: Optional[str] = None,
        token: Optional[str] = None,
        ca_path: Optional[str] = None,
        probe_timeout: float = 5.0,
        watch_timeout_s: int = 30,
        probe_ttl: float = 60.0,
        role_label: str = DEFAULT_ROLE_LABEL,
    ):
        self.namespace = namespace
        self.port = port
        self.label_selector = label_selector
        # Pod label carrying the disagg role ("prefill"/"decode"); the
        # helm role pools stamp it (stackcheck SC707 pins the agreement).
        self.role_label = role_label
        self.api_server = (api_server or in_cluster_api_server()).rstrip("/")
        self._token = token
        self._ca_path = ca_path
        self._probe_timeout = probe_timeout
        self._watch_timeout_s = watch_timeout_s
        self._probe_ttl = probe_ttl
        self._probe_times: Dict[str, float] = {}  # pod name -> last probe
        self._endpoints: Dict[str, EndpointInfo] = {}  # pod name -> endpoint
        self._task: Optional[asyncio.Task] = None
        self._session: Optional[aiohttp.ClientSession] = None
        self._resource_version: Optional[str] = None
        self._ready = asyncio.Event()  # first list complete

    # -- auth plumbing -----------------------------------------------------

    def _load_token(self) -> Optional[str]:
        if self._token is not None:
            return self._token
        # Re-read per call: the kubelet rotates bound SA tokens on disk
        # (~1h expiry); a token baked in at startup would 401 forever.
        token_path = os.path.join(SA_DIR, "token")
        if os.path.exists(token_path):
            with open(token_path) as f:
                return f.read().strip()
        return None

    def _ssl_context(self):
        ca = self._ca_path or os.path.join(SA_DIR, "ca.crt")
        if self.api_server.startswith("https://"):
            if os.path.exists(ca):
                return ssl.create_default_context(cafile=ca)
            return ssl.create_default_context()
        return None

    def _headers(self) -> Dict[str, str]:
        token = self._load_token()
        return {"Authorization": f"Bearer {token}"} if token else {}

    def _pods_url(self, watch: bool = False) -> str:
        from urllib.parse import quote

        url = f"{self.api_server}/api/v1/namespaces/{quote(self.namespace)}/pods"
        params = []
        if self.label_selector:
            # Set-based selectors contain spaces/parens: must be encoded.
            params.append(f"labelSelector={quote(self.label_selector)}")
        if watch:
            params.append("watch=1")
            params.append(f"timeoutSeconds={self._watch_timeout_s}")
            if self._resource_version:
                params.append(f"resourceVersion={quote(self._resource_version)}")
        return url + ("?" + "&".join(params) if params else "")

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> None:
        # No default headers: the bearer token is attached per API-server
        # request only — the model probe talks plaintext HTTP to engine
        # pods and must never carry the service-account credential.
        self._session = aiohttp.ClientSession()
        self._task = asyncio.create_task(self._watch_loop())
        # Serve from the first pod list as soon as it lands (or after 5 s —
        # an unreachable API server must not wedge router startup).
        try:
            await asyncio.wait_for(self._ready.wait(), timeout=5.0)
        except asyncio.TimeoutError:
            logger.warning("K8s discovery: initial pod list still pending")

    async def close(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except (asyncio.CancelledError, Exception):
                pass
            self._task = None
        if self._session is not None:
            await self._session.close()
            self._session = None

    def get_endpoint_info(self) -> List[EndpointInfo]:
        return list(self._endpoints.values())

    def get_health(self) -> bool:
        return self._task is not None and not self._task.done()

    # -- watch loop --------------------------------------------------------

    async def _watch_loop(self) -> None:
        ssl_ctx = self._ssl_context()
        while True:
            try:
                await self._list_pods(ssl_ctx)
                self._ready.set()
                await self._watch_pods(ssl_ctx)
            except asyncio.CancelledError:
                raise
            except Exception as e:
                logger.warning("K8s watcher error: %s; retrying", e)
                await asyncio.sleep(0.5)

    async def _list_pods(self, ssl_ctx) -> None:
        async with self._session.get(
            self._pods_url(), ssl=ssl_ctx, headers=self._headers()
        ) as resp:
            resp.raise_for_status()
            body = await resp.json()
        self._resource_version = body.get("metadata", {}).get("resourceVersion")
        seen = set()
        for pod in body.get("items", []):
            name = pod.get("metadata", {}).get("name")
            seen.add(name)
            await self._on_pod_event("MODIFIED", pod)
        # Pods gone between watches (e.g. deleted while disconnected).
        for name in [n for n in self._endpoints if n not in seen]:
            self._delete_engine(name)

    async def _watch_pods(self, ssl_ctx) -> None:
        url = self._pods_url(watch=True)
        timeout = aiohttp.ClientTimeout(total=None, sock_read=self._watch_timeout_s + 30)
        async with self._session.get(
            url, ssl=ssl_ctx, timeout=timeout, headers=self._headers()
        ) as resp:
            if resp.status == 410:  # resourceVersion too old: re-list
                self._resource_version = None
                return
            resp.raise_for_status()
            async for line in self._iter_lines(resp.content):
                event = json.loads(line)
                etype = event.get("type")
                obj = event.get("object", {})
                if etype == "BOOKMARK":
                    self._resource_version = obj.get("metadata", {}).get(
                        "resourceVersion"
                    )
                    continue
                if etype == "ERROR":
                    # Typically 410 Gone wrapped in a Status object.
                    self._resource_version = None
                    return
                rv = obj.get("metadata", {}).get("resourceVersion")
                if rv:
                    self._resource_version = rv
                await self._on_pod_event(etype, obj)

    @staticmethod
    async def _iter_lines(stream: aiohttp.StreamReader):
        """Split the watch stream on newlines ourselves: aiohttp's readline
        has a ~64 KiB line limit, and a single pod object with managedFields
        routinely exceeds it — hitting the limit raised ValueError every
        watch cycle and silently degraded the watcher into a list-poll loop."""
        buf = bytearray()
        async for chunk in stream.iter_any():
            buf.extend(chunk)
            while True:
                nl = buf.find(b"\n")
                if nl < 0:
                    break
                line = bytes(buf[:nl]).strip()
                del buf[: nl + 1]
                if line:
                    yield line
        tail = bytes(buf).strip()
        if tail:
            yield tail

    # -- pod event handling (reference :184-239 semantics) -----------------

    @staticmethod
    def _pod_ready(pod: dict) -> bool:
        statuses = pod.get("status", {}).get("containerStatuses") or []
        return bool(statuses) and all(s.get("ready") for s in statuses)

    async def _probe_models(self, pod_ip: str) -> Optional[List[str]]:
        url = f"http://{pod_ip}:{self.port}/v1/models"
        try:
            timeout = aiohttp.ClientTimeout(total=self._probe_timeout)
            async with self._session.get(url, timeout=timeout) as resp:
                resp.raise_for_status()
                body = await resp.json()
            return [m["id"] for m in body.get("data", [])]
        except Exception as e:
            logger.warning("Model probe failed for %s: %s", url, e)
            return None

    async def _on_pod_event(self, etype: str, pod: dict) -> None:
        meta = pod.get("metadata", {})
        name = meta.get("name")
        if name is None:
            return
        pod_ip = pod.get("status", {}).get("podIP")
        if etype == "DELETED":
            self._delete_engine(name)
            return
        if etype not in ("ADDED", "MODIFIED"):
            return
        if pod_ip and self._pod_ready(pod):
            # Steady-state MODIFIED churn for an already-known pod at the
            # same IP must not trigger a blocking model probe on every event
            # (each probe serializes the whole watch stream for up to
            # probe_timeout).  A TTL bounds model-list staleness instead:
            # multi-model engines that load another model are picked up
            # within probe_ttl via the periodic re-list.
            existing = self._endpoints.get(name)
            if (
                existing is not None
                and existing.url == f"http://{pod_ip}:{self.port}"
                and time.time() - self._probe_times.get(name, 0.0) < self._probe_ttl
            ):
                return
            models = await self._probe_models(pod_ip)
            self._probe_times[name] = time.time()
            if models:
                labels = meta.get("labels", {})
                self._add_engine(name, pod_ip, models, labels)
                return
        # Not ready / no IP / probe failed: drop it if we had it.
        self._delete_engine(name)

    def _add_engine(
        self, name: str, pod_ip: str, models: List[str], labels: dict
    ) -> None:
        url = f"http://{pod_ip}:{self.port}"
        raw_role = labels.get(self.role_label) or None
        role = raw_role if raw_role in ENGINE_ROLES else None
        existing = self._endpoints.get(name)
        if (
            existing is not None
            and existing.url == url
            and existing.model_names == models
            and existing.role == role
        ):
            return  # steady-state MODIFIED churn
        if raw_role is not None and role is None:
            # After the churn short-circuit: one mislabeled pod must not
            # re-warn on every watch event.
            logger.warning(
                "Pod %s carries unknown %s=%r; treating as fused",
                name, self.role_label, raw_role,
            )
        logger.info(
            "Discovered engine %s at %s (models %s, role %s)",
            name, url, models, role or "fused",
        )
        self._endpoints[name] = EndpointInfo(
            url=url,
            model_names=models,
            added_timestamp=time.time(),
            model_label=labels.get("model"),
            pod_name=name,
            role=role,
        )

    def _delete_engine(self, name: str) -> None:
        self._probe_times.pop(name, None)
        if self._endpoints.pop(name, None) is not None:
            logger.info("Engine pod %s removed", name)
