"""Round-robin routing (reference RoundRobinRouter, routing_logic.py:45-76).

Fix over the reference: one counter *per model* instead of a single shared
counter, so interleaved traffic to different models cannot skew per-model
fairness (SURVEY.md section 7, "Reference bugs to avoid repeating").
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

from production_stack_tpu.router.routing.base import (
    RoutingInterface,
    exclude_prefill_role,
    require_endpoints,
)
from production_stack_tpu.router.service_discovery import EndpointInfo


class RoundRobinRouter(RoutingInterface):
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: Dict[str, int] = {}

    def route_request(
        self,
        endpoints: List[EndpointInfo],
        engine_stats,
        request_stats,
        request,
        request_json: Optional[Dict[str, Any]] = None,
    ) -> str:
        endpoints = require_endpoints(exclude_prefill_role(endpoints))
        # Sort by URL so the rotation order is stable across calls even if
        # discovery returns endpoints in a different order (reference sorts
        # the same way, routing_logic.py:73-74).
        ordered = sorted(endpoints, key=lambda ep: ep.url)
        # Key the counter on the *requested* model so interleaved traffic to
        # different models each sees its own fair rotation.
        model_key = (request_json or {}).get("model") or ",".join(
            sorted(ordered[0].model_names)
        ) or "<default>"
        with self._lock:
            count = self._counters.get(model_key, 0)
            self._counters[model_key] = count + 1
        return ordered[count % len(ordered)].url
