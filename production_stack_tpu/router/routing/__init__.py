"""Pluggable routing logic (reference: src/vllm_router/routers/routing_logic.py).

Algorithms:

* ``roundrobin`` — per-model round robin (fixes the reference's shared
  counter, routing_logic.py:73-76, which skews fairness across models).
* ``session`` — session affinity via consistent hashing with lowest-QPS
  fallback (reference SessionRouter, routing_logic.py:79-172).
* ``least_loaded`` — lowest engine queue depth (the second algorithm the
  reference's StaticRoute CRD advertises, staticroute_types.go:42).
* ``kv_aware`` — prefix-affinity + load-aware scoring; maximizes TPU HBM
  KV-cache reuse (capability the reference only gets implicitly through
  session stickiness).
* ``kv_aware_popularity`` — ``kv_aware`` plus the fleet-level
  prefix-popularity view: hot prefixes (the multi-round-QA shared system
  prompt) are served by a load-grown replica SET instead of one sticky
  owner, while long per-user tails stay session-sticky (kv_aware.py
  module docstring).
* ``disagg`` — two-phase disaggregated prefill/decode over the shared KV
  plane: prime a prefill-pool backend, hand the prefix chain off, decode
  on a decode-pool backend (DistServe/Splitwise analogue; the reference
  left this roadmap-only, README.md:57).
"""

from __future__ import annotations

from typing import Any

from production_stack_tpu.router.routing.base import RoutingInterface
from production_stack_tpu.router.routing.round_robin import RoundRobinRouter
from production_stack_tpu.router.routing.session import SessionRouter
from production_stack_tpu.router.routing.least_loaded import LeastLoadedRouter
from production_stack_tpu.router.routing.kv_aware import (
    KVAwareRouter,
    PopularityKVAwareRouter,
)
from production_stack_tpu.router.routing.disagg import DisaggRouter

ROUTING_SERVICE = "routing_logic"

_ALGORITHMS = {
    "roundrobin": RoundRobinRouter,
    "session": SessionRouter,
    "least_loaded": LeastLoadedRouter,
    "kv_aware": KVAwareRouter,
    "kv_aware_popularity": PopularityKVAwareRouter,
    "disagg": DisaggRouter,
}


def available_routing_logics():
    return sorted(_ALGORITHMS)


def build_routing_logic(name: str, **kwargs: Any) -> RoutingInterface:
    try:
        cls = _ALGORITHMS[name]
    except KeyError:
        raise ValueError(
            f"Unknown routing logic {name!r}; available: {available_routing_logics()}"
        ) from None
    return cls(**kwargs)


def initialize_routing_logic(registry, name: str, **kwargs: Any) -> RoutingInterface:
    """Build and register (reference initialize_routing_logic, routing_logic.py:176-187)."""
    return registry.set(ROUTING_SERVICE, build_routing_logic(name, **kwargs))


def reconfigure_routing_logic(registry, name: str, **kwargs: Any) -> RoutingInterface:
    """Atomic swap (reference purges SingletonMeta._instances, routing_logic.py:189-196)."""
    return registry.replace(ROUTING_SERVICE, lambda: build_routing_logic(name, **kwargs))


def get_routing_logic(registry) -> RoutingInterface:
    return registry.require(ROUTING_SERVICE)
