"""Disaggregated prefill/decode routing policy (DistServe/Splitwise
analogue over the async KV plane — ROADMAP item 1).

The ``disagg`` policy two-phases each completion request:

1. **Prefill phase** — pick a prefill-pool backend by *queued prompt
   tokens* (the scraped ``tpu:queued_prompt_tokens`` gauge: prefill work
   is prompt-token-bound, so queue depth in requests under-weights long
   prompts) and issue a prime call (``x-disagg-phase: prefill``).  The
   engine runs the prefill, **eagerly** exports the prefix chain to the
   shared KV store, and returns a handoff token instead of generating.
2. **Decode phase** — route the real generation to a decode-pool backend
   (least-loaded), forwarding the handoff token; the decode engine's
   admission-time remote prefetch (PR 4) imports the chain so decode
   never executes prompt tokens.

The two-phase orchestration itself (the prime HTTP call, deadline
re-check between phases, per-role breaker handling, fused fallback) lives
in ``router/services/request_service/disagg.py`` — this class is the
*selection* policy plus the ``two_phase`` capability marker the request
path keys on.  When either pool is unavailable the policy degrades to a
fused single-backend route (``route_request`` over decode-capable
endpoints), never a 500.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from production_stack_tpu.router.routing.base import (
    RoutingInterface,
    exclude_prefill_role,
    require_endpoints,
)
from production_stack_tpu.router.service_discovery import EndpointInfo


class DisaggRouter(RoutingInterface):
    """Selection policy for the two-phase disagg data path."""

    # Capability marker the request path uses to enter the two-phase flow
    # (duck-typed so tests can fake it without importing this module).
    two_phase = True

    def _load(self, url: str, engine_stats, request_stats) -> float:
        if url in engine_stats:
            es = engine_stats[url]
            return float(es.num_running_requests + es.num_queuing_requests)
        if url in request_stats:
            rs = request_stats[url]
            return float(rs.in_prefill_requests + rs.in_decoding_requests)
        return 0.0

    def select_prefill(
        self,
        prefill_pool: List[EndpointInfo],
        engine_stats: Optional[Dict[str, Any]] = None,
        request_stats: Optional[Dict[str, Any]] = None,
    ) -> str:
        """Prefill-pool pick: least queued **prompt tokens** (scraped
        ``tpu:queued_prompt_tokens``), tie-broken by queue depth then URL.
        Unscraped backends count as idle, like every other policy."""
        engine_stats = engine_stats or {}
        request_stats = request_stats or {}

        def key(ep: EndpointInfo):
            queued_tokens = 0.0
            if ep.url in engine_stats:
                queued_tokens = float(
                    getattr(engine_stats[ep.url], "queued_prompt_tokens", 0.0)
                )
            return (
                queued_tokens,
                self._load(ep.url, engine_stats, request_stats),
                ep.url,
            )

        return min(require_endpoints(prefill_pool), key=key).url

    def route_request(
        self,
        endpoints: List[EndpointInfo],
        engine_stats,
        request_stats,
        request,
        request_json: Optional[Dict[str, Any]] = None,
    ) -> str:
        """Decode-phase (and fused-fallback) pick: least-loaded over
        decode-capable endpoints.  Decode work is slot-bound, not
        prompt-token-bound — with the prefix imported, admitting another
        stream costs one batch slot regardless of prompt length."""
        endpoints = require_endpoints(exclude_prefill_role(endpoints))
        engine_stats = engine_stats or {}
        request_stats = request_stats or {}
        return min(
            endpoints,
            key=lambda ep: (
                self._load(ep.url, engine_stats, request_stats), ep.url
            ),
        ).url
