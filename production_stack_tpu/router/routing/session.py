"""Session-affinity routing via consistent hashing.

Reference counterpart: SessionRouter, routing_logic.py:79-172 — session key
taken from a configurable header; requests without the header fall back to
lowest-QPS; the hash ring is synced to endpoint churn so only sessions on
removed engines are remapped.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

from production_stack_tpu.router.routing.base import (
    RoutingInterface,
    exclude_prefill_role,
    lowest_qps_url,
    require_endpoints,
)
from production_stack_tpu.router.service_discovery import EndpointInfo
from production_stack_tpu.utils.hashring import HashRing


class SessionRouter(RoutingInterface):
    def __init__(self, session_key: str = "x-user-id"):
        if not session_key:
            raise ValueError("session routing requires a session_key header name")
        self.session_key = session_key
        self._lock = threading.Lock()
        self._ring = HashRing()

    def _sync_ring(self, endpoints: List[EndpointInfo]) -> None:
        self._ring.sync(ep.url for ep in endpoints)

    def route_request(
        self,
        endpoints: List[EndpointInfo],
        engine_stats,
        request_stats,
        request,
        request_json: Optional[Dict[str, Any]] = None,
    ) -> str:
        # Sessions are generation streams: a dedicated prefill-pool
        # backend must never become a session's sticky home.
        endpoints = require_endpoints(exclude_prefill_role(endpoints))
        session_id = request.headers.get(self.session_key)
        if not session_id:
            return lowest_qps_url(endpoints, request_stats or {})
        with self._lock:
            self._sync_ring(endpoints)
            url = self._ring.get_node(session_id)
        assert url is not None
        return url
