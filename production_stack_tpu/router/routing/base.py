"""Routing interface (reference RoutingInterface, routing_logic.py:22-42)."""

from __future__ import annotations

from typing import Any, Dict, List, Mapping, Optional, Protocol, runtime_checkable

from production_stack_tpu.router.service_discovery import EndpointInfo
from production_stack_tpu.router.stats.engine_stats import EngineStats
from production_stack_tpu.router.stats.request_stats import RequestStats


@runtime_checkable
class Request(Protocol):
    """The slice of an HTTP request routing needs (duck-typed so tests can
    use plain fakes, mirroring src/tests/test_session_router.py:6-19)."""

    @property
    def headers(self) -> Mapping[str, str]: ...  # noqa: E704


class RoutingInterface:
    def route_request(
        self,
        endpoints: List[EndpointInfo],
        engine_stats: Dict[str, EngineStats],
        request_stats: Dict[str, RequestStats],
        request: Request,
        request_json: Optional[Dict[str, Any]] = None,
    ) -> str:
        """Pick a backend URL for this request.

        ``endpoints`` is already filtered to those serving the requested
        model (reference request.py:169).  Raises ValueError when empty.
        """
        raise NotImplementedError


def require_endpoints(endpoints: List[EndpointInfo]) -> List[EndpointInfo]:
    if not endpoints:
        raise ValueError("No serving-engine endpoints available for this model")
    return endpoints


def lowest_qps_url(
    endpoints: List[EndpointInfo], request_stats: Dict[str, RequestStats]
) -> str:
    """Endpoint with lowest observed QPS; unseen endpoints count as idle
    (reference SessionRouter._qps_routing, routing_logic.py:94-115)."""
    best_url, best_qps = None, float("inf")
    for ep in require_endpoints(endpoints):
        qps = request_stats[ep.url].qps if ep.url in request_stats else 0.0
        if qps < best_qps:
            best_url, best_qps = ep.url, qps
    assert best_url is not None
    return best_url
