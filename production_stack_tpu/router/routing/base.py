"""Routing interface (reference RoutingInterface, routing_logic.py:22-42)."""

from __future__ import annotations

from typing import Any, Dict, List, Mapping, Optional, Protocol, runtime_checkable

from production_stack_tpu.router.service_discovery import EndpointInfo
from production_stack_tpu.router.stats.engine_stats import EngineStats
from production_stack_tpu.router.stats.request_stats import RequestStats


@runtime_checkable
class Request(Protocol):
    """The slice of an HTTP request routing needs (duck-typed so tests can
    use plain fakes, mirroring src/tests/test_session_router.py:6-19)."""

    @property
    def headers(self) -> Mapping[str, str]: ...  # noqa: E704


class RoutingInterface:
    def route_request(
        self,
        endpoints: List[EndpointInfo],
        engine_stats: Dict[str, EngineStats],
        request_stats: Dict[str, RequestStats],
        request: Request,
        request_json: Optional[Dict[str, Any]] = None,
    ) -> str:
        """Pick a backend URL for this request.

        ``endpoints`` is already filtered to those serving the requested
        model (reference request.py:169).  Raises ValueError when empty.
        """
        raise NotImplementedError


def require_endpoints(endpoints: List[EndpointInfo]) -> List[EndpointInfo]:
    if not endpoints:
        raise ValueError("No serving-engine endpoints available for this model")
    return endpoints


def exclude_prefill_role(endpoints: List[EndpointInfo]) -> List[EndpointInfo]:
    """Decode-capable selection: dedicated prefill-pool backends only run
    the disagg prime phase — a session/KV-affinity/least-loaded pick must
    not park a generation stream on one (it would decode at prefill-pool
    batch shapes AND re-introduce the interference disaggregation exists
    to remove).  Dedicated ``encode``-pool backends are likewise reserved
    for embed/rerank/score traffic (docs/router.md "Encode lanes"): a
    generation stream parked there would contend with the batched encode
    windows the pool exists to isolate.  Degrades rather than 500s: when
    ONLY reserved-role backends exist they stay eligible (any engine can
    still decode; the role only steers pool placement)."""
    capable = [
        ep for ep in endpoints
        if getattr(ep, "role", None) not in ("prefill", "encode")
    ]
    return capable if capable else endpoints


def prefer_encode_pool(endpoints: List[EndpointInfo]) -> List[EndpointInfo]:
    """Encode-lane candidate selection (embeddings / rerank / score):
    dedicated ``encode``-role backends win outright when any exist; else
    fused role-less backends (they serve both surfaces); else the full
    list (a prefill/decode-only fleet still answers embeddings — degrade,
    never 503 a request some backend could serve)."""
    dedicated = [
        ep for ep in endpoints if getattr(ep, "role", None) == "encode"
    ]
    if dedicated:
        return dedicated
    fused = [
        ep for ep in endpoints if getattr(ep, "role", None) in (None, "")
    ]
    return fused if fused else endpoints


def filter_circuit_available(endpoints: List[EndpointInfo], breaker) -> List[EndpointInfo]:
    """Drop endpoints whose circuit breaker is open (docs/robustness.md):
    an opened backend receives NO traffic until a half-open probe
    succeeds.  When every endpoint is open the empty list propagates to
    ``require_endpoints`` and the request is shed with a 503 instead of
    burning connect timeouts on known-dead backends."""
    if breaker is None:
        return endpoints
    return [ep for ep in endpoints if breaker.available(ep.url)]


def deprioritize_backpressured(
    endpoints: List[EndpointInfo], breaker
) -> List[EndpointInfo]:
    """Routing weight drop for engines that answered 429 recently: prefer
    backends that are not shedding, but keep the backpressured set as the
    candidate pool of last resort (an overloaded engine still beats no
    engine — it sheds cheaply with another 429)."""
    if breaker is None:
        return endpoints
    relieved = [ep for ep in endpoints if not breaker.is_backpressured(ep.url)]
    return relieved if relieved else endpoints


def effective_load(url: str, engine_stats, request_stats) -> float:
    """Backend load for routing decisions: the MAX of the scraped engine
    running+waiting queue depth and the router's own synchronous
    in-flight count for that backend.  Scrape-only reads go stale for a
    whole scrape interval — a burst arriving between scrapes would pile
    onto one "least loaded" backend until the next scrape catches up;
    the router's own in-flight counter moves per request, so the fresh
    local lower bound caps the pileup.  (In multi-router deployments the
    scraped value still contributes the OTHER routers' load — hence max,
    not replacement.)  Shared by LeastLoadedRouter and KVAwareRouter so
    the invariant cannot drift between them."""
    scraped = 0.0
    if url in engine_stats:
        es = engine_stats[url]
        scraped = float(es.num_running_requests + es.num_queuing_requests)
    local = 0.0
    if url in request_stats:
        rs = request_stats[url]
        local = float(rs.in_prefill_requests + rs.in_decoding_requests)
    return max(scraped, local)


def lowest_qps_url(
    endpoints: List[EndpointInfo], request_stats: Dict[str, RequestStats]
) -> str:
    """Endpoint with lowest observed QPS; unseen endpoints count as idle
    (reference SessionRouter._qps_routing, routing_logic.py:94-115)."""
    best_url, best_qps = None, float("inf")
    for ep in require_endpoints(endpoints):
        qps = request_stats[ep.url].qps if ep.url in request_stats else 0.0
        if qps < best_qps:
            best_url, best_qps = ep.url, qps
    assert best_url is not None
    return best_url
