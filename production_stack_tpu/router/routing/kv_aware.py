"""KV-cache-aware (prefix-affinity) routing, with an optional
fleet-level prefix-popularity view (``kv_aware_popularity``).

Not present in the reference: its only KV-locality mechanism is session
stickiness (routing_logic.py:79-172) + LMCache offload.  On TPU, prefix reuse
is the dominant TTFT lever (the multi-round-QA workload re-sends a 1,000-token
system prompt and up to 20,000 tokens of history every round, see
benchmarks/multi-round-qa/run.sh:43-48) — so the router itself tracks which
engine has most recently served each prompt prefix and routes to maximize
paged-KV prefix-cache hits, balanced against queue depth.

Mechanism: the request's prompt text is split into fixed-size chunks; each
cumulative chunk-prefix hash is remembered in a bounded LRU mapping to the
engine that served it.  Scoring an endpoint combines (matched prefix length)
against (engine load), so a hot engine does not melt down just because it
owns a popular prefix.

Popularity mode (``popularity=True``, routing logic
``kv_aware_popularity``): the single-owner LRU has an adversarial failure
under SHARED prefixes — the fleet's hottest prefix (the multi-round-QA
shared system prompt) is the head of EVERY user's chain, so whichever
backend served the last request owns the head, every other user's
affinity walk breaks at chunk 0, and the hot prefix both funnels onto
one replica (DistServe/Splitwise's locality warning) and flip-flops
ownership so even deep per-user tails score zero.  Popularity mode fixes
both: each digest carries a decayed request-frequency counter; digests
past ``hot_threshold`` are HOT and matched against a *replica set* of
owners instead of one backend.  The set grows when every current member
is degraded enough (queue/capacity score) that a non-member wins the
load-vs-affinity score — the new member cold-prefills once (or warms the
prefix through the shared KV store when one is configured: the PR-4
prefetch plane imports the exported chain instead of recomputing) and
serves it hot from then on; members idle past ``replica_ttl_s`` decay
out, and a digest whose popularity decays below half the threshold
demotes back to single-owner.  Long per-user tails stay effectively
session-sticky: their digests never get hot, so the deep chain match
keeps pulling a user to the backend holding their history unless it is
badly overloaded.

The owner map is additionally corrected against scraped REALITY, not
just the router's own routing history: the engine exports its
prefix-cache truth (``tpu:prefix_cache_blocks`` size gauge +
hit/query-token counters, threaded through ``EngineStats``), and a
backend whose cached-block count collapses between scrapes (restart,
cache flush) is purged from the owner map and every replica set — the
router must not keep scoring affinity toward a cache that no longer
exists.

Hash contract: with a ``tokenize`` callable the router derives its prefix
keys from the ENGINE'S OWN chain — ``prefix_block_hashes`` over token-id
blocks (engine/kv/block_pool.py, a pure-python module), byte-identical to
the engine's ``_seq_prefix_hashes`` and therefore to the content keys
under which engines export/import KV blocks through the shared store.  A
silent divergence here would steer "affine" requests to replicas whose
store entries never match (tests/test_kv_prefetch.py asserts the
contract).  Without a tokenizer the router falls back to the text-chunk
heuristic, which still captures affinity but makes no key-equality
claim.
"""

from __future__ import annotations

import hashlib
import json
import threading
import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional

from production_stack_tpu.router.routing.base import (
    RoutingInterface,
    effective_load,
    exclude_prefill_role,
    require_endpoints,
)
from production_stack_tpu.router.service_discovery import EndpointInfo


def extract_prompt_text(request_json: Optional[Dict[str, Any]]) -> str:
    """Canonical prompt text from a chat-completion or completion body."""
    if not request_json:
        return ""
    if "messages" in request_json:
        parts = []
        for msg in request_json.get("messages") or []:
            content = msg.get("content") if isinstance(msg, dict) else None
            if isinstance(content, str):
                parts.append(f"{msg.get('role', '')}:{content}")
            elif isinstance(content, list):  # multimodal content parts
                parts.append(json.dumps(content, sort_keys=True, default=str))
        return "\n".join(parts)
    prompt = request_json.get("prompt")
    if isinstance(prompt, str):
        return prompt
    if isinstance(prompt, list):
        return "\n".join(str(p) for p in prompt)
    return ""


class KVAwareRouter(RoutingInterface):
    def __init__(
        self,
        chunk_chars: int = 1024,
        max_tracked_prefixes: int = 65536,
        load_tradeoff: float = 2.0,
        tokenize=None,
        token_block_size: int = 16,
        popularity: bool = False,
        hot_threshold: float = 8.0,
        popularity_halflife_s: float = 60.0,
        max_replicas: int = 8,
        replica_ttl_s: float = 300.0,
        hot_credit_cap: float = 0.5,
        shared_threshold: float = 32.0,
        reconcile_interval_s: float = 5.0,
        clock=time.monotonic,
    ):
        self.chunk_chars = int(chunk_chars)
        self.max_tracked_prefixes = int(max_tracked_prefixes)
        # How many chunks of prefix-match one unit of queue depth is worth.
        self.load_tradeoff = float(load_tradeoff)
        # Optional exact-contract mode: tokenize(text) -> List[int]; the
        # prefix keys then ARE the engine's KV-block content-key chain
        # (module docstring), so affinity scoring tracks real store/
        # prefix-cache hits instead of a text heuristic.
        self.tokenize = tokenize
        self.token_block_size = int(token_block_size)
        # -- popularity view (module docstring) ---------------------------
        self.popularity = bool(popularity)
        self.hot_threshold = float(hot_threshold)
        self.popularity_halflife_s = float(popularity_halflife_s)
        self.max_replicas = int(max_replicas)
        self.replica_ttl_s = float(replica_ttl_s)
        # Affinity-credit cap for fleet-SHARED chunks (the >= 3-way
        # chain-divergence / shared_threshold classifier below): shared
        # content is cheap to replicate (one cold prefill — or a store
        # import, when a store is configured — and it serves hot
        # forever), so matching it must not let a replica hoard traffic
        # deep into queueing the way an irreplaceable per-user tail
        # legitimately does.  Non-shared chunks (tails) keep full
        # per-chunk credit even when hot: losing one means re-prefilling
        # a user's whole history somewhere else.  The cap IS the
        # replication pacing: a non-member wins the score (and joins the
        # replica set) once every member queues deeper than
        # ``load_tradeoff * hot_credit_cap``.
        self.hot_credit_cap = float(hot_credit_cap)
        # Decayed popularity past which a digest classifies fleet-SHARED
        # even before it spreads to 3 owners (the head crosses this
        # within the first seconds of fleet traffic; a per-user tail —
        # bumped once per conversation round — never gets near it).
        self.shared_threshold = float(shared_threshold)
        self.reconcile_interval_s = float(reconcile_interval_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._prefix_owner: "OrderedDict[str, str]" = OrderedDict()
        # digest -> [decayed_count, stamp, successor_digests]; LRU-bounded
        # with the owner map.  ``successor_digests`` (capped small set)
        # counts the DISTINCT next-chunk digests observed after this one
        # — the structural fleet-shared classifier: a divergence point
        # where >= 3 different chains continue is the boundary of
        # genuinely shared content (the system prompt ends and per-user
        # text begins), and every chunk at or before such a boundary is
        # shared by construction.  A per-user tail chunk's successor is
        # the SAME digest every round (chain hashing is deterministic),
        # so tails never classify shared no matter how often one user
        # re-asks.
        self._pop: "OrderedDict[str, list]" = OrderedDict()
        # Digests known to be fleet-shared content (prefix-closed: a
        # divergence point marks itself and everything before it).
        self._shared: set = set()
        # Hot digests and their replica sets (digest -> url -> last stamp).
        self._hot: set = set()
        self._replicas: Dict[str, "OrderedDict[str, float]"] = {}
        # Monotonic promotion counter (tpu_router:prefix_hot_total feed).
        self.hot_promotions_total = 0
        # Scraped prefix-cache truth per url: last cached-blocks reading.
        self._truth_blocks: Dict[str, float] = {}
        self._last_reconcile = 0.0

    def _prefix_hashes(self, text: str) -> List[str]:
        if self.tokenize is not None:
            from production_stack_tpu.engine.kv.block_pool import (
                prefix_block_hashes,
            )

            return [
                digest.hex()
                for digest in prefix_block_hashes(
                    self.tokenize(text), self.token_block_size
                )
            ]
        # FULL chunks only, mirroring the engine's prefix_block_hashes
        # (full blocks, leave-one-token): a partial final chunk's digest
        # changes every time the conversation grows, so it never matches
        # anything next round — and worse, it manufactures a fresh
        # "successor" per round, which would falsely classify a per-user
        # tail as a fleet-shared divergence point (popularity mode).
        # Prompts shorter than one chunk hash as a single whole-text
        # chunk so short-prompt affinity still exists.
        hashes = []
        h = hashlib.blake2b(digest_size=8)
        n_full = len(text) // self.chunk_chars
        if n_full == 0 and text:
            h.update(text.encode("utf-8"))
            return [h.hexdigest()]
        for i in range(n_full):
            start = i * self.chunk_chars
            h.update(text[start : start + self.chunk_chars].encode("utf-8"))
            hashes.append(h.hexdigest())
        return hashes

    # -- popularity bookkeeping (all under self._lock) ---------------------

    def _decayed(self, digest: str, now: float) -> float:
        entry = self._pop.get(digest)
        if entry is None:
            return 0.0
        value, stamp = entry[0], entry[1]
        if now > stamp:
            value *= 0.5 ** ((now - stamp) / self.popularity_halflife_s)
        return value

    def _bump_popularity(self, hashes: List[str], now: float) -> None:
        """Decayed per-digest request counters + successor tracking;
        crossing ``hot_threshold`` promotes to hot (replica-set
        matching), decaying below half of it demotes back to
        single-owner.  Chunks at or before a divergence point (>= 3
        distinct successors) — or past ``shared_threshold`` popularity —
        classify as fleet-SHARED, which caps their affinity credit."""
        shared_upto = -1
        for i, digest in enumerate(hashes):
            entry = self._pop.get(digest)
            value = self._decayed(digest, now) + 1.0
            successors = entry[2] if entry is not None else set()
            if i + 1 < len(hashes) and len(successors) < 3:
                successors.add(hashes[i + 1])
            self._pop[digest] = [value, now, successors]
            self._pop.move_to_end(digest)
            if digest not in self._shared and (
                len(successors) >= 3 or value >= self.shared_threshold
            ):
                self._shared.add(digest)
            if digest in self._shared:
                shared_upto = i
            if digest not in self._hot and value >= self.hot_threshold:
                self._hot.add(digest)
                self.hot_promotions_total += 1
                reps: "OrderedDict[str, float]" = OrderedDict()
                # Seed from (and retire) the single-owner entry: a hot
                # digest is represented by its replica set alone.
                owner = self._prefix_owner.pop(digest, None)
                if owner is not None:
                    reps[owner] = now
                self._replicas[digest] = reps
                # Event-site metric (lazy: routing stays importable in
                # bare unit-test contexts; the services layer owns the
                # prometheus objects).
                try:
                    from production_stack_tpu.router.services import (
                        metrics_service as ms,
                    )

                    ms.prefix_hot_total.inc()
                except Exception:  # pragma: no cover - metrics optional
                    pass
        # Backward propagation: everything at or before the deepest
        # shared chunk in THIS chain is a prefix of shared content.
        for j in range(shared_upto + 1):
            self._shared.add(hashes[j])
        while len(self._pop) > self.max_tracked_prefixes:
            evicted, _ = self._pop.popitem(last=False)
            self._shared.discard(evicted)
            self._demote(evicted)

    def _demote(self, digest: str) -> None:
        self._hot.discard(digest)
        reps = self._replicas.pop(digest, None)
        if reps:
            # Fall back to single-owner = the most recently routed member.
            last_url = max(reps, key=lambda u: reps[u])
            self._prefix_owner[digest] = last_url
            self._prefix_owner.move_to_end(digest)

    def _live_replicas(self, digest: str, now: float):
        """The digest's replica set with TTL-expired members dropped
        (the decay-shrink half of the grow/shrink contract)."""
        reps = self._replicas.get(digest)
        if not reps:
            return None
        for url in [u for u, stamp in reps.items()
                    if now - stamp > self.replica_ttl_s]:
            del reps[url]
        return reps

    def _matched_chunks(self, hashes: List[str], url: str, now: float) -> float:
        """Affinity CREDIT (not raw chunk count) of ``url`` for this
        chain.  Non-SHARED chunks (user-private content, hot or cold)
        count 1.0 each; fleet-SHARED chunks (the >= 3-way-divergence /
        shared_threshold classifier) count toward an aggregate of at
        most ``hot_credit_cap`` — shared content is replicable, tails
        are not (see __init__).  Walk semantics: an unmatched private
        chunk BREAKS the walk (chain affinity ends there); an unmatched
        SHARED chunk is transparent (no credit, no break) so a private-
        tail match survives the shared head's ownership churn."""
        full = 0
        shared = 0
        for digest in hashes:
            # Fleet-SHARED content (at/before a >= 3-way chain
            # divergence, or past shared_threshold popularity) is
            # replicable, so (a) its match credit is capped, and (b) a
            # MISMATCH on it never breaks the walk: shared spans carry
            # no placement information — a user's round-2 request must
            # still reach its private-tail match on the backend that
            # served round 1 even while the shared head's ownership is
            # churning through its pre-promotion warmup.  A hot digest
            # that is NOT shared is a user's own re-requested tail: full
            # credit, with the replica set acting as MEMORY — a user
            # bounced between two backends can return to either without
            # the single-owner LRU forgetting the warm one.
            is_shared = self.popularity and digest in self._shared
            matched = False
            if self.popularity and digest in self._hot:
                reps = self._live_replicas(digest, now)
                matched = bool(reps) and url in reps
            else:
                matched = self._prefix_owner.get(digest) == url
            if matched:
                if is_shared:
                    shared += 1
                else:
                    full += 1
                continue
            if is_shared:
                continue  # transparent: no credit, no break
            break
        if not self.popularity:
            return float(full)
        return float(full) + min(float(shared), self.hot_credit_cap)

    def _note_route(self, hashes: List[str], url: str, now: float) -> None:
        """Record the routing decision: hot digests gain/refresh ``url``
        in their replica set (growth happens exactly when load made a
        non-member win the score); cold digests keep LRU single-owner
        semantics (per-user tails: latest backend owns the tail)."""
        for digest in hashes:
            if self.popularity and digest in self._hot:
                if self._decayed(digest, now) < self.hot_threshold / 2.0:
                    self._demote(digest)
                    self._prefix_owner[digest] = url
                    self._prefix_owner.move_to_end(digest)
                    continue
                reps = self._replicas.setdefault(digest, OrderedDict())
                reps[url] = now
                while len(reps) > self.max_replicas:
                    # Evict the stalest member (least recently routed).
                    stalest = min(reps, key=lambda u: reps[u])
                    del reps[stalest]
                continue
            self._prefix_owner[digest] = url
            self._prefix_owner.move_to_end(digest)
        while len(self._prefix_owner) > self.max_tracked_prefixes:
            self._prefix_owner.popitem(last=False)

    # -- scraped-truth reconcile + pod-churn prune -------------------------

    def _maybe_reconcile(self, engine_stats, now: float) -> None:
        """Correct the owner map against scraped prefix-cache truth: a
        backend whose ``tpu:prefix_cache_blocks`` collapsed between
        scrapes restarted (or flushed) — every prefix the router believes
        resident there is gone, so purge it from the owner map and the
        replica sets instead of routing affinity toward an empty cache."""
        if now - self._last_reconcile < self.reconcile_interval_s:
            return
        self._last_reconcile = now
        reset_urls = []
        for url, es in engine_stats.items():
            blocks = float(getattr(es, "prefix_cache_blocks", 0.0) or 0.0)
            prev = self._truth_blocks.get(url)
            self._truth_blocks[url] = blocks
            # A collapse (>75% drop from a non-trivial size) is a cache
            # reset; LRU churn shrinks gradually and never looks like
            # this between adjacent scrapes.
            if prev is not None and prev >= 8.0 and blocks < 0.25 * prev:
                reset_urls.append(url)
        for url in reset_urls:
            self._purge_url(url)

    def _purge_url(self, url: str) -> None:
        for digest in [d for d, u in self._prefix_owner.items() if u == url]:
            del self._prefix_owner[digest]
        for digest, reps in list(self._replicas.items()):
            reps.pop(url, None)

    def prune(self, live_urls) -> List[str]:
        """Drop owner-map/popularity state for backends that left
        discovery (pod churn) — same contract as ``CapacityModel.prune``
        / ``CircuitBreaker.prune``; returns the removed urls.  Without
        this, stale owners keep pulling affinity score toward dead
        endpoints and the replica sets grow unboundedly across churn."""
        live = set(live_urls)
        gone: set = set()
        with self._lock:
            for digest, url in list(self._prefix_owner.items()):
                if url not in live:
                    del self._prefix_owner[digest]
                    gone.add(url)
            for digest, reps in list(self._replicas.items()):
                for url in [u for u in reps if u not in live]:
                    del reps[url]
                    gone.add(url)
            for url in [u for u in self._truth_blocks if u not in live]:
                del self._truth_blocks[url]
                gone.add(url)
        return sorted(gone)

    def popularity_snapshot(self) -> Dict[str, float]:
        """Live popularity-view stats for the router /metrics render."""
        now = self._clock()
        with self._lock:
            sizes = []
            for digest in list(self._hot):
                reps = self._live_replicas(digest, now)
                sizes.append(len(reps) if reps else 0)
            return {
                "hot_prefixes": len(self._hot),
                "replica_set_max": max(sizes) if sizes else 0,
                "hot_promotions_total": self.hot_promotions_total,
            }

    # -- routing -----------------------------------------------------------

    def route_request(
        self,
        endpoints: List[EndpointInfo],
        engine_stats,
        request_stats,
        request,
        request_json: Optional[Dict[str, Any]] = None,
    ) -> str:
        # Prefix affinity is a DECODE-locality signal: learning a prefix
        # owner in the prefill pool would steer every affine follow-up to
        # a backend that never serves generations.
        endpoints = require_endpoints(exclude_prefill_role(endpoints))
        engine_stats = engine_stats or {}
        request_stats = request_stats or {}
        hashes = self._prefix_hashes(extract_prompt_text(request_json))
        now = self._clock()

        def load(url: str) -> float:
            # max(scraped queue depth, synchronous router-side in-flight)
            # — the shared stale-scrape-pileup guard (routing/base.py).
            return effective_load(url, engine_stats, request_stats)

        with self._lock:
            if self.popularity:
                self._bump_popularity(hashes, now)
                if engine_stats:
                    self._maybe_reconcile(engine_stats, now)
            best_url, best_score = None, float("inf")
            for ep in sorted(endpoints, key=lambda e: e.url):
                affinity = (
                    self._matched_chunks(hashes, ep.url, now) if hashes else 0
                )
                score = load(ep.url) - self.load_tradeoff * affinity
                if score < best_score:
                    best_url, best_score = ep.url, score
            assert best_url is not None
            self._note_route(hashes, best_url, now)
        return best_url


class PopularityKVAwareRouter(KVAwareRouter):
    """``kv_aware`` with the fleet prefix-popularity view on — registered
    as routing logic ``kv_aware_popularity`` so the A/B ladder, helm
    values, and dynamic config can select it by name."""

    def __init__(self, **kwargs):
        kwargs.setdefault("popularity", True)
        super().__init__(**kwargs)
