"""KV-cache-aware (prefix-affinity) routing.

Not present in the reference: its only KV-locality mechanism is session
stickiness (routing_logic.py:79-172) + LMCache offload.  On TPU, prefix reuse
is the dominant TTFT lever (the multi-round-QA workload re-sends a 1,000-token
system prompt and up to 20,000 tokens of history every round, see
benchmarks/multi-round-qa/run.sh:43-48) — so the router itself tracks which
engine has most recently served each prompt prefix and routes to maximize
paged-KV prefix-cache hits, balanced against queue depth.

Mechanism: the request's prompt text is split into fixed-size chunks; each
cumulative chunk-prefix hash is remembered in a bounded LRU mapping to the
engine that served it.  Scoring an endpoint combines (matched prefix length)
against (engine load), so a hot engine does not melt down just because it
owns a popular prefix.

Hash contract: with a ``tokenize`` callable the router derives its prefix
keys from the ENGINE'S OWN chain — ``prefix_block_hashes`` over token-id
blocks (engine/kv/block_pool.py, a pure-python module), byte-identical to
the engine's ``_seq_prefix_hashes`` and therefore to the content keys
under which engines export/import KV blocks through the shared store.  A
silent divergence here would steer "affine" requests to replicas whose
store entries never match (tests/test_kv_prefetch.py asserts the
contract).  Without a tokenizer the router falls back to the text-chunk
heuristic, which still captures affinity but makes no key-equality
claim.
"""

from __future__ import annotations

import hashlib
import json
import threading
from collections import OrderedDict
from typing import Any, Dict, List, Optional

from production_stack_tpu.router.routing.base import (
    RoutingInterface,
    exclude_prefill_role,
    require_endpoints,
)
from production_stack_tpu.router.service_discovery import EndpointInfo


def extract_prompt_text(request_json: Optional[Dict[str, Any]]) -> str:
    """Canonical prompt text from a chat-completion or completion body."""
    if not request_json:
        return ""
    if "messages" in request_json:
        parts = []
        for msg in request_json.get("messages") or []:
            content = msg.get("content") if isinstance(msg, dict) else None
            if isinstance(content, str):
                parts.append(f"{msg.get('role', '')}:{content}")
            elif isinstance(content, list):  # multimodal content parts
                parts.append(json.dumps(content, sort_keys=True, default=str))
        return "\n".join(parts)
    prompt = request_json.get("prompt")
    if isinstance(prompt, str):
        return prompt
    if isinstance(prompt, list):
        return "\n".join(str(p) for p in prompt)
    return ""


class KVAwareRouter(RoutingInterface):
    def __init__(
        self,
        chunk_chars: int = 1024,
        max_tracked_prefixes: int = 65536,
        load_tradeoff: float = 2.0,
        tokenize=None,
        token_block_size: int = 16,
    ):
        self.chunk_chars = int(chunk_chars)
        self.max_tracked_prefixes = int(max_tracked_prefixes)
        # How many chunks of prefix-match one unit of queue depth is worth.
        self.load_tradeoff = float(load_tradeoff)
        # Optional exact-contract mode: tokenize(text) -> List[int]; the
        # prefix keys then ARE the engine's KV-block content-key chain
        # (module docstring), so affinity scoring tracks real store/
        # prefix-cache hits instead of a text heuristic.
        self.tokenize = tokenize
        self.token_block_size = int(token_block_size)
        self._lock = threading.Lock()
        self._prefix_owner: "OrderedDict[str, str]" = OrderedDict()

    def _prefix_hashes(self, text: str) -> List[str]:
        if self.tokenize is not None:
            from production_stack_tpu.engine.kv.block_pool import (
                prefix_block_hashes,
            )

            return [
                digest.hex()
                for digest in prefix_block_hashes(
                    self.tokenize(text), self.token_block_size
                )
            ]
        hashes = []
        h = hashlib.blake2b(digest_size=8)
        for start in range(0, len(text), self.chunk_chars):
            h.update(text[start : start + self.chunk_chars].encode("utf-8"))
            hashes.append(h.hexdigest())
        return hashes

    def _matched_chunks(self, hashes: List[str], url: str) -> int:
        matched = 0
        for digest in hashes:
            if self._prefix_owner.get(digest) == url:
                matched += 1
            else:
                break
        return matched

    def route_request(
        self,
        endpoints: List[EndpointInfo],
        engine_stats,
        request_stats,
        request,
        request_json: Optional[Dict[str, Any]] = None,
    ) -> str:
        # Prefix affinity is a DECODE-locality signal: learning a prefix
        # owner in the prefill pool would steer every affine follow-up to
        # a backend that never serves generations.
        endpoints = require_endpoints(exclude_prefill_role(endpoints))
        engine_stats = engine_stats or {}
        request_stats = request_stats or {}
        hashes = self._prefix_hashes(extract_prompt_text(request_json))

        def load(url: str) -> float:
            if url in engine_stats:
                es = engine_stats[url]
                return float(es.num_running_requests + es.num_queuing_requests)
            if url in request_stats:
                rs = request_stats[url]
                return float(rs.in_prefill_requests + rs.in_decoding_requests)
            return 0.0

        with self._lock:
            best_url, best_score = None, float("inf")
            for ep in sorted(endpoints, key=lambda e: e.url):
                affinity = self._matched_chunks(hashes, ep.url) if hashes else 0
                score = load(ep.url) - self.load_tradeoff * affinity
                if score < best_score:
                    best_url, best_score = ep.url, score
            assert best_url is not None
            for digest in hashes:
                self._prefix_owner[digest] = best_url
                self._prefix_owner.move_to_end(digest)
            while len(self._prefix_owner) > self.max_tracked_prefixes:
                self._prefix_owner.popitem(last=False)
        return best_url
