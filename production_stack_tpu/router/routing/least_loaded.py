"""Least-loaded routing.

The reference's StaticRoute CRD advertises ``roundrobin|least_loaded``
(src/router-controller/api/v1alpha1/staticroute_types.go:42) but the Python
router never implements the latter; we do.  Load = the MAX of the scraped
engine running+waiting queue depth and the router's own synchronous
in-flight count for that backend.  Scrape-only reads go stale for a whole
scrape interval — a burst arriving between scrapes would pile onto one
"least loaded" backend until the next scrape catches up (and could push it
past its admission bound while the rest of the fleet idles); the router's
own in-flight counter moves per request, so the fresh local lower bound
caps the pileup.  (In multi-router deployments the scraped value still
contributes the OTHER routers' load — hence max, not replacement.)
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from production_stack_tpu.router.routing.base import (
    RoutingInterface,
    effective_load,
    exclude_prefill_role,
    require_endpoints,
)
from production_stack_tpu.router.service_discovery import EndpointInfo


class LeastLoadedRouter(RoutingInterface):
    def route_request(
        self,
        endpoints: List[EndpointInfo],
        engine_stats,
        request_stats,
        request,
        request_json: Optional[Dict[str, Any]] = None,
    ) -> str:
        endpoints = require_endpoints(exclude_prefill_role(endpoints))
        engine_stats = engine_stats or {}
        request_stats = request_stats or {}
        return min(
            endpoints,
            key=lambda ep: (
                effective_load(ep.url, engine_stats, request_stats), ep.url
            ),
        ).url
