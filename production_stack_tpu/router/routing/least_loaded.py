"""Least-loaded routing.

The reference's StaticRoute CRD advertises ``roundrobin|least_loaded``
(src/router-controller/api/v1alpha1/staticroute_types.go:42) but the Python
router never implements the latter; we do.  Load = engine running+waiting
queue depth from scraped stats, falling back to router-side in-flight counts
for engines that have not been scraped yet.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from production_stack_tpu.router.routing.base import (
    RoutingInterface,
    exclude_prefill_role,
    require_endpoints,
)
from production_stack_tpu.router.service_discovery import EndpointInfo


class LeastLoadedRouter(RoutingInterface):
    def route_request(
        self,
        endpoints: List[EndpointInfo],
        engine_stats,
        request_stats,
        request,
        request_json: Optional[Dict[str, Any]] = None,
    ) -> str:
        endpoints = require_endpoints(exclude_prefill_role(endpoints))
        engine_stats = engine_stats or {}
        request_stats = request_stats or {}

        def load(ep: EndpointInfo) -> float:
            if ep.url in engine_stats:
                es = engine_stats[ep.url]
                return float(es.num_running_requests + es.num_queuing_requests)
            if ep.url in request_stats:
                rs = request_stats[ep.url]
                return float(rs.in_prefill_requests + rs.in_decoding_requests)
            return 0.0

        return min(endpoints, key=lambda ep: (load(ep), ep.url)).url
