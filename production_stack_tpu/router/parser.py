"""Router CLI (reference counterpart: src/vllm_router/parsers/parser.py:30-209)."""

from __future__ import annotations

import argparse

from production_stack_tpu.router.routing import available_routing_logics
from production_stack_tpu.utils.net import (
    parse_static_aliases,
    parse_static_models,
    parse_static_urls,
    validate_url,
)
from production_stack_tpu.version import __version__


def parse_args(argv=None) -> argparse.Namespace:
    parser = argparse.ArgumentParser(
        prog="tpu-router",
        description="OpenAI-compatible L7 router for TPU serving engines",
    )
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=8001)

    # Service discovery (reference parser.py:62-96).
    parser.add_argument(
        "--service-discovery", choices=["static", "k8s"], default="static"
    )
    parser.add_argument(
        "--static-backends",
        default=None,
        help="Comma-separated engine base URLs (static discovery)",
    )
    parser.add_argument(
        "--static-models",
        default=None,
        help="Comma-separated model names, one entry per backend; "
        "use ';' inside an entry for multi-model engines",
    )
    parser.add_argument(
        "--static-model-labels", default=None, help="Comma-separated model labels"
    )
    parser.add_argument(
        "--static-model-types",
        default=None,
        help="Comma-separated model types (chat|completion|embeddings|rerank|score)",
    )
    parser.add_argument(
        "--static-probe-models",
        action="store_true",
        help="Probe <backend>/v1/models at startup for backends without a "
        "configured model list",
    )
    parser.add_argument(
        "--static-backend-roles",
        default=None,
        help="Comma-separated role-pool assignments, one entry per "
        "backend: 'prefill', 'decode', 'encode' (dedicated "
        "embed/rerank/score pool), or empty (fused).  Required by "
        "--routing-logic disagg under static discovery",
    )
    parser.add_argument("--k8s-namespace", default="default")
    parser.add_argument("--k8s-port", type=int, default=8000)
    parser.add_argument(
        "--k8s-label-selector", default="", help="Label selector for engine pods"
    )
    parser.add_argument(
        "--k8s-role-label",
        default="app.production-stack-tpu/role",
        help="Pod label carrying the role-pool assignment "
        "('prefill'/'decode'/'encode'); the helm role pools stamp it on "
        "engine pods (stackcheck SC707 pins the chart<->flag agreement)",
    )

    # Routing (reference parser.py:98-116).
    parser.add_argument(
        "--routing-logic", choices=available_routing_logics(), default="roundrobin"
    )
    parser.add_argument(
        "--session-key", default=None, help="Session-affinity header name"
    )
    # KV-affinity scoring (routing logics kv_aware / kv_aware_popularity).
    parser.add_argument(
        "--kv-chunk-chars", type=int, default=1024,
        help="prefix-chunk granularity (chars) for the KV-affinity hash "
        "chain; smaller chunks resolve affinity on shorter prompts at "
        "more tracking overhead",
    )
    parser.add_argument(
        "--kv-affinity-tradeoff", type=float, default=2.0,
        help="how many matched prefix chunks one unit of backend queue "
        "depth is worth in the load-vs-affinity score; higher = stickier "
        "(fewer history re-prefills), lower = more load-balanced",
    )
    # Fleet prefix-popularity view (routing logic kv_aware_popularity;
    # routing/kv_aware.py module docstring): hot-prefix classification +
    # replica-set replication knobs.  Harmless on other routing logics.
    parser.add_argument(
        "--kv-popularity-hot-threshold", type=float, default=8.0,
        help="decayed per-prefix request count past which a prefix is HOT "
        "and served by a replica set instead of one sticky owner (the "
        "multi-round-QA shared system prompt crosses this within its "
        "first seconds of fleet traffic)",
    )
    parser.add_argument(
        "--kv-popularity-halflife-s", type=float, default=60.0,
        help="exponential-decay half-life of the per-prefix popularity "
        "counters; also paces hot->cold demotion",
    )
    parser.add_argument(
        "--kv-popularity-max-replicas", type=int, default=8,
        help="replica-set size cap per hot prefix (growth is load-driven: "
        "a new member joins only when every current member is degraded "
        "enough to lose the load-vs-affinity score)",
    )
    parser.add_argument(
        "--kv-popularity-replica-ttl-s", type=float, default=300.0,
        help="replica-set members not routed to for this long decay out "
        "(the shrink half of the grow/shrink contract)",
    )
    parser.add_argument(
        "--kv-popularity-hot-credit-cap", type=float, default=0.5,
        help="affinity-credit cap (in chunks) for fleet-SHARED prefixes "
        "(content at/before a >=3-way chain divergence, e.g. the shared "
        "system prompt): shared content is replicable, so its match "
        "credit is bounded — a replica-set member may queue at most "
        "tradeoff*cap deeper than an idle backend before the prefix "
        "replicates onto a new member; user-private chunks (tails) keep "
        "full per-chunk credit even when hot",
    )
    parser.add_argument(
        "--model-aliases",
        default=None,
        help="Comma-separated alias:model pairs rewritten before routing",
    )

    # Stats (reference parser.py:118-139).
    parser.add_argument("--engine-stats-interval", type=float, default=10.0)
    parser.add_argument("--request-stats-window", type=float, default=60.0)

    # Overload protection + graceful lifecycle (docs/robustness.md).
    parser.add_argument(
        "--no-circuit-breaker",
        action="store_true",
        help="disable the per-backend circuit breaker (every request then "
        "re-probes dead backends at connect-timeout cost — the pre-breaker "
        "behavior)",
    )
    parser.add_argument(
        "--breaker-failure-threshold", type=int, default=5,
        help="consecutive connect/5xx failures that open a backend's "
        "circuit (engine 429s never count — they are backpressure)",
    )
    parser.add_argument(
        "--breaker-open-s", type=float, default=2.0,
        help="base open window before the first half-open probe; doubles "
        "per consecutive open (capped at 60s)",
    )
    parser.add_argument(
        "--retry-budget", type=int, default=3,
        help="max connect-stage failover attempts per request beyond the "
        "routed backend (bounds failover amplification under overload)",
    )
    parser.add_argument(
        "--stream-idle-timeout-s", type=float, default=300.0,
        help="tear down a backend stream that produces no bytes for this "
        "long (stalled engine); the teardown aborts the engine-side "
        "sequence via disconnect.  0 disables",
    )
    parser.add_argument(
        "--drain-grace-s", type=float, default=30.0,
        help="on SIGTERM or POST /drain: flip /ready to 503, reject new "
        "data-plane work with 503 + Connection: close, let in-flight "
        "streams finish up to this many seconds, then exit 0",
    )

    # Fleet-level admission control (router/capacity.py): the router
    # learns each backend's capacity online from the stats plane and
    # sheds with a structured 429 + Retry-After when estimated fleet
    # headroom is exhausted — before any engine queue grows.
    parser.add_argument(
        "--no-fleet-admission",
        action="store_true",
        help="disable router-level fleet admission control (overload then "
        "queues per-engine until each backend's local bound 429s — the "
        "pre-fleet-admission behavior)",
    )
    parser.add_argument(
        "--fleet-default-slots", type=float, default=64.0,
        help="capacity-model prior: max useful concurrency assumed per "
        "backend until the stats plane teaches a better estimate.  "
        "Deliberately optimistic — the router must never shed work the "
        "fleet hasn't PROVEN it cannot take (observed queueing, SLO "
        "breach, or an engine 429 all clamp the estimate down instantly)",
    )
    parser.add_argument(
        "--fleet-slo-p95-itl-s", type=float, default=2.0,
        help="windowed p95 inter-token-latency SLO; a backend breaching "
        "it has its capacity estimate clamped to its current concurrency",
    )
    parser.add_argument(
        "--fleet-slo-p95-ttft-s", type=float, default=10.0,
        help="windowed p95 TTFT SLO for the capacity model (same clamp "
        "semantics as the ITL SLO)",
    )
    parser.add_argument(
        "--fleet-low-priority-headroom", type=float, default=0.15,
        help="degradation ladder: shed priority>0 (speculative/batch) "
        "requests once fleet headroom falls below this fraction of fleet "
        "capacity, so interactive traffic never queues behind them",
    )

    # Request tracing (production_stack_tpu/obs): per-request span
    # timelines at GET /debug/requests, joined with the engine's at
    # /debug/requests/{id}.
    parser.add_argument(
        "--no-tracing",
        action="store_true",
        help="disable request tracing (obs.tracing=off): no spans, no "
        "/debug/requests ring; request-id echo and latency histograms stay",
    )
    parser.add_argument(
        "--trace-ring-size", type=int, default=256,
        help="completed request timelines kept for GET /debug/requests",
    )
    parser.add_argument(
        "--trace-ring-bytes", type=int, default=8 * 1024 * 1024,
        help="byte bound on the completed-trace ring (JSON-encoded size; "
        "evictions past it count in tpu_router:obs_trace_dropped_total; "
        "0 = count bound only)",
    )
    parser.add_argument(
        "--log-stats", action="store_true", help="Periodically log the stats planes"
    )
    parser.add_argument("--log-stats-interval", type=float, default=10.0)

    # Dynamic config (reference parser.py:141-150).
    parser.add_argument(
        "--dynamic-config-json",
        default=None,
        help="Path to a hot-reloaded router config JSON (written by the operator)",
    )

    # Files / batch API (reference parser.py:152-176).
    parser.add_argument("--enable-batch-api", action="store_true")
    parser.add_argument("--file-storage-class", default="local_file")
    parser.add_argument("--file-storage-path", default="/tmp/tpu_router_storage")
    parser.add_argument("--batch-processor", default="local")

    # Experimental feature gates (reference feature_gates.py:80-142).
    parser.add_argument(
        "--feature-gates",
        default="",
        help="K8s-style gates, e.g. SemanticCache=true,PIIDetection=true",
    )
    parser.add_argument("--semantic-cache-model", default="hash")
    parser.add_argument("--semantic-cache-dir", default=None)
    parser.add_argument("--semantic-cache-threshold", type=float, default=0.95)
    parser.add_argument(
        "--pii-analyzer",
        default="regex",
        choices=["regex", "secrets", "strict", "ner"],
        help="regex: classic PII patterns; secrets: credential material "
        "(API keys, private keys, IBANs); strict: both; ner: strict plus "
        "a transformers token-classification model (PERSON/LOCATION/"
        "ORGANIZATION entities; needs PSTPU_PII_NER_MODEL pointing at a "
        "local checkpoint — the reference's presidio-analyzer analogue)",
    )

    # Encode-lane semantic cache (router/encode_cache.py): answers repeat
    # /v1/embeddings (and exact-hit rerank/score) from the router with
    # zero engine work.  Off by default (max-bytes 0) — caching is a
    # correctness-visible behavior the operator must opt into.
    parser.add_argument(
        "--encode-cache-max-bytes", type=int, default=0,
        help="byte budget for the encode-lane semantic cache (exact tier "
        "keyed on the chunk-hash chain; LRU + TTL bounded); 0 disables "
        "the cache entirely",
    )
    parser.add_argument(
        "--encode-cache-ttl-s", type=float, default=300.0,
        help="max age of a cached encode answer before it is re-computed "
        "(staleness bound; entries also evict under the byte budget)",
    )
    parser.add_argument(
        "--encode-cache-similarity-threshold", type=float, default=0.0,
        help="cosine similarity past which a near-duplicate single-text "
        "embedding request may be answered from the similarity tier "
        "(vectorized via the embed lane itself); 0 keeps the cache "
        "exact-only",
    )

    parser.add_argument("--request-rewriter", default="noop")
    parser.add_argument("--log-level", default="info")
    parser.add_argument(
        "--version", action="version", version=f"%(prog)s {__version__}"
    )

    args = parser.parse_args(argv)
    validate_args(args)
    return args


def validate_args(args: argparse.Namespace) -> None:
    """Cross-flag validation (reference parser.py:30-51)."""
    if args.service_discovery == "static":
        if not args.static_backends:
            raise ValueError("static service discovery requires --static-backends")
        urls = parse_static_urls(args.static_backends)
        for url in urls:
            if not validate_url(url):
                raise ValueError(f"Invalid static backend URL: {url}")
        if args.static_models:
            models = parse_static_models(args.static_models)
            if len(models) != len(urls):
                raise ValueError(
                    f"--static-models has {len(models)} entries but "
                    f"--static-backends has {len(urls)}"
                )
        elif not args.static_probe_models:
            raise ValueError(
                "static discovery needs --static-models or --static-probe-models"
            )
        for flag, value in [
            ("--static-model-labels", args.static_model_labels),
            ("--static-model-types", args.static_model_types),
        ]:
            if value:
                entries = parse_static_models(value)
                if len(entries) != len(urls):
                    raise ValueError(
                        f"{flag} has {len(entries)} entries but "
                        f"--static-backends has {len(urls)}"
                    )
        if args.static_backend_roles:
            # split(","), not parse_static_models: empty entries are
            # meaningful here (fused backends in a mixed fleet).
            roles = [r.strip() for r in args.static_backend_roles.split(",")]
            if len(roles) != len(urls):
                raise ValueError(
                    f"--static-backend-roles has {len(roles)} entries but "
                    f"--static-backends has {len(urls)}"
                )
            for role in roles:
                if role and role not in ("prefill", "decode", "encode"):
                    raise ValueError(
                        f"--static-backend-roles entries must be 'prefill', "
                        f"'decode', 'encode', or empty; got {role!r}"
                    )
    if args.routing_logic == "session" and not args.session_key:
        raise ValueError("--routing-logic session requires --session-key")
    if args.kv_chunk_chars < 1:
        raise ValueError("--kv-chunk-chars must be >= 1")
    if args.kv_affinity_tradeoff < 0:
        raise ValueError("--kv-affinity-tradeoff must be >= 0")
    if args.kv_popularity_hot_threshold <= 0:
        raise ValueError("--kv-popularity-hot-threshold must be > 0")
    if args.kv_popularity_halflife_s <= 0:
        raise ValueError("--kv-popularity-halflife-s must be > 0")
    if args.kv_popularity_max_replicas < 1:
        raise ValueError("--kv-popularity-max-replicas must be >= 1")
    if args.kv_popularity_replica_ttl_s <= 0:
        raise ValueError("--kv-popularity-replica-ttl-s must be > 0")
    if args.kv_popularity_hot_credit_cap < 0:
        raise ValueError("--kv-popularity-hot-credit-cap must be >= 0")
    if (
        args.routing_logic == "disagg"
        and args.service_discovery == "static"
        and not args.static_backend_roles
    ):
        # Without roles the prefill pool is permanently empty and every
        # request silently runs fused — fail at boot, not via metrics.
        raise ValueError(
            "--routing-logic disagg under static discovery requires "
            "--static-backend-roles (at least one 'prefill' and one "
            "'decode' backend)"
        )
    if args.model_aliases:
        parse_static_aliases(args.model_aliases)
    if args.batch_processor not in ("local",):
        raise ValueError(f"Unknown batch processor {args.batch_processor!r}")
    if args.breaker_failure_threshold < 1:
        raise ValueError("--breaker-failure-threshold must be >= 1")
    if args.breaker_open_s <= 0:
        raise ValueError("--breaker-open-s must be > 0")
    if args.retry_budget < 0:
        raise ValueError("--retry-budget must be >= 0")
    if args.drain_grace_s < 0:
        raise ValueError("--drain-grace-s must be >= 0")
    if args.encode_cache_max_bytes < 0:
        raise ValueError("--encode-cache-max-bytes must be >= 0")
    if args.encode_cache_ttl_s <= 0:
        raise ValueError("--encode-cache-ttl-s must be > 0")
    if not 0.0 <= args.encode_cache_similarity_threshold <= 1.0:
        raise ValueError(
            "--encode-cache-similarity-threshold must be in [0, 1]"
        )
    if args.fleet_default_slots < 1:
        raise ValueError("--fleet-default-slots must be >= 1")
    if args.fleet_slo_p95_itl_s <= 0 or args.fleet_slo_p95_ttft_s <= 0:
        raise ValueError("fleet SLO thresholds must be > 0")
    if not (0.0 <= args.fleet_low_priority_headroom <= 1.0):
        raise ValueError("--fleet-low-priority-headroom must be in [0, 1]")
