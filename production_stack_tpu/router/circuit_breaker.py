"""Per-backend circuit breaker for the router's proxy path.

Standard three-state machine (docs/robustness.md "Circuit breaker"):

* **closed** — traffic flows; consecutive connect/5xx failures count up.
* **open** — entered after ``failure_threshold`` consecutive failures; the
  backend receives NO traffic until the open window expires.  The window
  grows exponentially (``open_base_s * 2^(opens-1)``, capped at
  ``open_max_s``) across consecutive opens, so a persistently dead backend
  is probed ever more rarely.
* **half_open** — one probe request is allowed through after the window;
  success closes the breaker, failure re-opens it with a doubled window.

Engine 429s are *backpressure*, not failures: the engine is alive and
explicitly shedding, so a 429 resets the failure count (the connect
succeeded) and instead marks the backend backpressured for ``Retry-After``
seconds — the routing layer deprioritizes it while alternatives exist, but
the breaker never opens on it (opening would amplify the overload onto the
remaining replicas).

Single-event-loop use only (the router is one asyncio loop): no locking.
Mutating transitions happen in ``on_attempt`` — ``available()`` is the
pure read the endpoint filter uses, so filtering N candidates cannot burn
the half-open probe slot of a backend routing then doesn't pick.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Dict, Optional

CLOSED, HALF_OPEN, OPEN = "closed", "half_open", "open"
# tpu_router:circuit_state gauge encoding.
STATE_VALUES = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}


@dataclasses.dataclass
class _BackendState:
    state: str = CLOSED
    failures: int = 0  # consecutive connect/5xx failures while closed
    opens: int = 0  # consecutive opens -> exponential window
    open_until: float = 0.0
    # While half_open: when a lost probe (client vanished mid-flight)
    # stops blocking the next one.
    probe_retry_at: float = 0.0
    backpressure_until: float = 0.0


class CircuitBreaker:
    def __init__(
        self,
        failure_threshold: int = 5,
        open_base_s: float = 2.0,
        open_max_s: float = 60.0,
        probe_timeout_s: float = 30.0,
        clock=time.time,
    ):
        if failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        self.failure_threshold = int(failure_threshold)
        self.open_base_s = float(open_base_s)
        self.open_max_s = float(open_max_s)
        self.probe_timeout_s = float(probe_timeout_s)
        self._clock = clock
        self._states: Dict[str, _BackendState] = {}

    def _st(self, url: str) -> _BackendState:
        st = self._states.get(url)
        if st is None:
            st = self._states[url] = _BackendState()
        return st

    # -- reads (endpoint filtering) ----------------------------------------

    def available(self, url: str) -> bool:
        """May this backend receive a request right now?  Pure read: an
        open breaker whose window expired reports available (the probe
        slot is consumed by on_attempt only if routing picks it)."""
        st = self._states.get(url)
        if st is None or st.state == CLOSED:
            return True
        now = self._clock()
        if st.state == OPEN:
            return now >= st.open_until
        return now >= st.probe_retry_at  # half_open: probe slot in flight

    def is_backpressured(self, url: str) -> bool:
        st = self._states.get(url)
        return st is not None and self._clock() < st.backpressure_until

    def state_value(self, url: str) -> int:
        st = self._states.get(url)
        return STATE_VALUES[st.state] if st is not None else 0

    def snapshot(self) -> Dict[str, int]:
        """url -> state gauge value (tpu_router:circuit_state)."""
        return {url: STATE_VALUES[st.state] for url, st in self._states.items()}

    def prune(self, live_urls) -> list:
        """Drop state for backends no longer in discovery; returns the
        removed urls so the metrics layer can retire their gauge labels.
        Without this, weeks of pod churn (every rolling update mints new
        pod IPs) would grow _states and the circuit_state label set
        without bound."""
        live = set(live_urls)
        gone = [url for url in self._states if url not in live]
        for url in gone:
            del self._states[url]
        return gone

    # -- transitions (proxy loop) ------------------------------------------

    def on_attempt(self, url: str) -> bool:
        """Claim permission to send one request.  Transitions an expired
        open breaker to half_open and consumes its single probe slot.
        False = the caller must skip this backend."""
        st = self._st(url)
        if st.state == CLOSED:
            return True
        now = self._clock()
        if st.state == OPEN:
            if now < st.open_until:
                return False
            st.state = HALF_OPEN
            st.probe_retry_at = now + self.probe_timeout_s
            return True
        # half_open: one probe at a time, recoverable if the probe is lost.
        if now < st.probe_retry_at:
            return False
        st.probe_retry_at = now + self.probe_timeout_s
        return True

    def on_success(self, url: str) -> None:
        st = self._st(url)
        st.state = CLOSED
        st.failures = 0
        st.opens = 0

    def on_failure(self, url: str) -> None:
        """A connect failure or 5xx response from this backend."""
        st = self._st(url)
        now = self._clock()
        if st.state == HALF_OPEN:
            self._open(st, now)
            return
        st.failures += 1
        if st.failures >= self.failure_threshold:
            self._open(st, now)

    def on_backpressure(self, url: str, retry_after_s: Optional[float]) -> None:
        """An engine 429: reachable but shedding.  Never opens the
        breaker; clears the consecutive-failure count (the connect
        succeeded) and deprioritizes the backend for the advertised
        window (routing weight drop)."""
        st = self._st(url)
        if st.state != CLOSED:
            # A half-open probe answered 429: the backend is back.
            self.on_success(url)
            st = self._st(url)
        st.failures = 0
        window = retry_after_s if retry_after_s and retry_after_s > 0 else 1.0
        st.backpressure_until = self._clock() + float(window)

    def _open(self, st: _BackendState, now: float) -> None:
        st.opens += 1
        window = min(
            self.open_max_s, self.open_base_s * (2 ** (st.opens - 1))
        )
        st.state = OPEN
        st.open_until = now + window
        st.failures = 0
