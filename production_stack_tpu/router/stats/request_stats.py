"""Per-engine request lifecycle statistics with sliding windows.

Reference counterpart: src/vllm_router/stats/request_stats.py:20-282
(RequestStats, MovingAverageMonitor, RequestStatsMonitor).

Bugs in the reference deliberately fixed here (SURVEY.md section 7):

* the latency / decoding-length monitors were write-orphaned — allocated at
  request_stats.py:122-123 but never ``update()``-ed, so the router's
  ``/metrics`` exported frozen zeros.  Here ``on_request_complete`` feeds
  end-to-end latency, and inter-token latency is derived from the streaming
  chunk callbacks.
* the router-side queueing delay the reference dashboard charts but never
  measures (``vllm:router_queueing_delay_seconds``, SURVEY.md section 5)
  is measured here: time between router receive and backend connect.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from typing import Deque, Dict, Optional, Tuple

from production_stack_tpu.obs.histogram import Histogram


@dataclasses.dataclass
class RequestStats:
    """Snapshot of one engine's request-level stats."""

    qps: float = 0.0
    ttft: float = 0.0  # seconds, sliding-window average
    in_prefill_requests: int = 0
    in_decoding_requests: int = 0
    finished_requests: int = 0
    uncompleted_requests: int = 0
    latency: float = 0.0  # end-to-end seconds, sliding-window average
    itl: float = 0.0  # inter-token latency seconds, sliding-window average
    queueing_delay: float = 0.0  # router-side, seconds
    decoding_length: float = 0.0  # avg streamed chunks per finished request
    # Windowed tail latencies (NOT the cumulative histograms below): the
    # online capacity model's SLO signal must reflect the last window,
    # not the whole process lifetime (router/capacity.py).
    itl_p95: float = 0.0
    ttft_p95: float = 0.0
    # Compile-excluded windowed TTFT p95: samples whose first chunk the
    # engine stamped ``"compile": true`` (an XLA compile fired inside
    # the request's dispatches) are cold-start, not steady state, and
    # are kept OUT of this quantile — the raw ttft_p95 above still sees
    # every sample, so the gap between the two IS the compile cost.
    ttft_clean_p95: float = 0.0


class SlidingWindow:
    """Timestamped samples over the last ``window`` seconds."""

    def __init__(self, window: float):
        self.window = window
        self._samples: Deque[Tuple[float, float]] = deque()

    def update(self, timestamp: float, value: float) -> None:
        self._samples.append((timestamp, value))
        self._expire(timestamp)

    def _expire(self, now: float) -> None:
        cutoff = now - self.window
        while self._samples and self._samples[0][0] < cutoff:
            self._samples.popleft()

    def count(self, now: Optional[float] = None) -> int:
        if now is not None:
            self._expire(now)
        return len(self._samples)

    def average(self, now: Optional[float] = None) -> float:
        if now is not None:
            self._expire(now)
        if not self._samples:
            return 0.0
        return sum(v for _, v in self._samples) / len(self._samples)

    def rate(self, now: Optional[float] = None) -> float:
        """Samples per second over the window."""
        if now is None:
            now = time.time()
        self._expire(now)
        return len(self._samples) / self.window

    def quantile(self, q: float, now: Optional[float] = None) -> float:
        """Windowed quantile of the sample VALUES (nearest-rank on a
        sorted copy; 0.0 when empty).  O(n log n) on the window — called
        from the capacity model's rate-limited refresh, not per request."""
        if now is not None:
            self._expire(now)
        if not self._samples:
            return 0.0
        values = sorted(v for _, v in self._samples)
        idx = min(len(values) - 1, max(0, int(q * (len(values) - 1) + 0.5)))
        return values[idx]


class _EngineWindows:
    __slots__ = (
        "arrivals",
        "ttft",
        "ttft_clean",
        "latency",
        "itl",
        "queueing",
        "decoding_length",
        "finished",
        "in_prefill",
        "in_decoding",
        "hists",
    )

    def __init__(self, window: float):
        self.arrivals = SlidingWindow(window)
        self.ttft = SlidingWindow(window)
        # TTFT samples NOT compile-tainted by the engine (the first
        # response chunk carried no "compile": true marker).
        self.ttft_clean = SlidingWindow(window)
        self.latency = SlidingWindow(window)
        self.itl = SlidingWindow(window)
        self.queueing = SlidingWindow(window)
        self.decoding_length = SlidingWindow(window)
        self.finished = 0
        self.in_prefill = 0
        self.in_decoding = 0
        # Cumulative latency histograms (Prometheus model: no window) —
        # the tail-latency (p95/p99) counterpart of the averages above.
        # Keys match vocabulary.ROUTER_HISTOGRAMS.
        self.hists = {
            "ttft": Histogram(),
            "itl": Histogram(),
            "latency": Histogram(),
            "queueing": Histogram(),
        }


class RequestStatsMonitor:
    """Tracks request lifecycle per engine URL.

    Lifecycle callbacks, called from the proxy data path
    (reference: services/request_service/request.py:68,95-107):

      on_new_request -> [on_backend_connected] -> on_request_response
      -> on_token_chunk* -> on_request_complete | on_request_failed
    """

    def __init__(self, sliding_window_size: float = 60.0):
        self.sliding_window_size = float(sliding_window_size)
        self._lock = threading.Lock()
        self._engines: Dict[str, _EngineWindows] = {}
        # (engine_url, request_id) -> timestamps
        self._arrived_at: Dict[Tuple[str, str], float] = {}
        self._first_token_at: Dict[Tuple[str, str], float] = {}
        self._last_token_at: Dict[Tuple[str, str], float] = {}
        self._chunk_count: Dict[Tuple[str, str], int] = {}

    def _windows(self, engine_url: str) -> _EngineWindows:
        if engine_url not in self._engines:
            self._engines[engine_url] = _EngineWindows(self.sliding_window_size)
        return self._engines[engine_url]

    # -- lifecycle ---------------------------------------------------------

    def on_new_request(self, engine_url: str, request_id: str, timestamp: float) -> None:
        with self._lock:
            w = self._windows(engine_url)
            w.arrivals.update(timestamp, 1.0)
            w.in_prefill += 1
            self._arrived_at[(engine_url, request_id)] = timestamp

    def on_backend_connected(
        self, engine_url: str, request_id: str, timestamp: float
    ) -> None:
        """Backend stream opened: records router-side queueing delay."""
        key = (engine_url, request_id)
        with self._lock:
            arrived = self._arrived_at.get(key)
            if arrived is not None:
                w = self._windows(engine_url)
                w.queueing.update(timestamp, timestamp - arrived)
                w.hists["queueing"].observe(timestamp - arrived)

    def on_request_response(
        self,
        engine_url: str,
        request_id: str,
        timestamp: float,
        compile_tainted: bool = False,
    ) -> None:
        """First token chunk arrived: TTFT; request moves prefill -> decode.
        ``compile_tainted`` (the engine's ``"compile": true`` first-chunk
        marker) keeps the sample out of the compile-excluded window."""
        key = (engine_url, request_id)
        with self._lock:
            if key in self._first_token_at:
                return
            self._first_token_at[key] = timestamp
            # Seed the inter-token clock and count the first chunk here; the
            # first chunk defines no ITL interval, so it must not produce an
            # ITL sample (n chunks -> n-1 intervals).
            self._last_token_at[key] = timestamp
            self._chunk_count[key] = 1
            w = self._windows(engine_url)
            arrived = self._arrived_at.get(key)
            if arrived is not None:
                w.ttft.update(timestamp, timestamp - arrived)
                w.hists["ttft"].observe(timestamp - arrived)
                if not compile_tainted:
                    w.ttft_clean.update(timestamp, timestamp - arrived)
            w.in_prefill = max(0, w.in_prefill - 1)
            w.in_decoding += 1

    def on_token_chunk(self, engine_url: str, request_id: str, timestamp: float) -> None:
        """Per streamed chunk: feeds inter-token latency."""
        key = (engine_url, request_id)
        with self._lock:
            last = self._last_token_at.get(key)
            if last is not None:
                w = self._windows(engine_url)
                w.itl.update(timestamp, timestamp - last)
                w.hists["itl"].observe(timestamp - last)
            self._last_token_at[key] = timestamp
            self._chunk_count[key] = self._chunk_count.get(key, 0) + 1

    def on_request_complete(
        self, engine_url: str, request_id: str, timestamp: float
    ) -> None:
        key = (engine_url, request_id)
        with self._lock:
            w = self._windows(engine_url)
            arrived = self._arrived_at.pop(key, None)
            if arrived is not None:
                w.latency.update(timestamp, timestamp - arrived)
                w.hists["latency"].observe(timestamp - arrived)
            if key in self._first_token_at:
                w.in_decoding = max(0, w.in_decoding - 1)
            else:
                # Completed without any token chunk (e.g. non-streaming).
                w.in_prefill = max(0, w.in_prefill - 1)
            w.finished += 1
            chunks = self._chunk_count.pop(key, 0)
            if chunks:
                w.decoding_length.update(timestamp, float(chunks))
            self._first_token_at.pop(key, None)
            self._last_token_at.pop(key, None)

    def on_request_failed(self, engine_url: str, request_id: str, timestamp: float) -> None:
        """Failed or client-aborted request: drop in-flight state, no latency sample."""
        key = (engine_url, request_id)
        with self._lock:
            w = self._windows(engine_url)
            if self._arrived_at.pop(key, None) is not None:
                if key in self._first_token_at:
                    w.in_decoding = max(0, w.in_decoding - 1)
                else:
                    w.in_prefill = max(0, w.in_prefill - 1)
            self._first_token_at.pop(key, None)
            self._last_token_at.pop(key, None)
            self._chunk_count.pop(key, None)

    # -- read side ---------------------------------------------------------

    def get_histograms(self) -> Dict[str, Dict[str, Histogram]]:
        """Per-engine cumulative latency histograms
        (keys: ttft / itl / latency / queueing).  The returned Histogram
        objects are live — callers read quantiles or render them, never
        mutate."""
        with self._lock:
            return {url: dict(w.hists) for url, w in self._engines.items()}

    def get_request_stats(
        self,
        current_time: Optional[float] = None,
        with_quantiles: bool = False,
    ) -> Dict[str, RequestStats]:
        """Per-engine snapshot.  ``with_quantiles`` additionally fills the
        windowed p95 fields (itl_p95/ttft_p95) — an O(n log n) sort over
        each window, so the per-request routing path leaves it off; the
        capacity model's rate-limited refresh and the metrics endpoint
        turn it on."""
        now = time.time() if current_time is None else current_time
        out: Dict[str, RequestStats] = {}
        with self._lock:
            uncompleted: Dict[str, int] = {}
            for (url, _), _ts in self._arrived_at.items():
                uncompleted[url] = uncompleted.get(url, 0) + 1
            for url, w in self._engines.items():
                out[url] = RequestStats(
                    qps=w.arrivals.rate(now),
                    ttft=w.ttft.average(now),
                    in_prefill_requests=w.in_prefill,
                    in_decoding_requests=w.in_decoding,
                    finished_requests=w.finished,
                    uncompleted_requests=uncompleted.get(url, 0),
                    latency=w.latency.average(now),
                    itl=w.itl.average(now),
                    queueing_delay=w.queueing.average(now),
                    decoding_length=w.decoding_length.average(now),
                    itl_p95=(
                        w.itl.quantile(0.95, now) if with_quantiles else 0.0
                    ),
                    ttft_p95=(
                        w.ttft.quantile(0.95, now) if with_quantiles else 0.0
                    ),
                    ttft_clean_p95=(
                        w.ttft_clean.quantile(0.95, now)
                        if with_quantiles else 0.0
                    ),
                )
        return out
