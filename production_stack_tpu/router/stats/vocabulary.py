"""Engine metric vocabulary — the single place TPU metric names live.

SURVEY.md section 7 "Hard parts" calls this out: the scraper, the Grafana
dashboard, the prometheus-adapter rule and the HPA all key off engine metric
names, and vLLM-TPU names differ from CUDA vLLM's (reference scraper
hard-codes ``vllm:gpu_cache_usage_perc`` etc. at
src/vllm_router/stats/engine_stats.py:52-55).

Canonical fields map to an ordered list of candidate Prometheus metric names;
the first present wins.  Our JAX engine emits the ``tpu:`` names; stock
vLLM(-TPU) emits the ``vllm:`` names — the scraper understands both, so the
router can front either engine.
"""

from __future__ import annotations

from typing import Dict, List

# Canonical engine-stat field -> candidate gauge names, most preferred first.
ENGINE_METRIC_CANDIDATES: Dict[str, List[str]] = {
    "num_running_requests": [
        "tpu:num_requests_running",
        "vllm:num_requests_running",
    ],
    "num_queuing_requests": [
        "tpu:num_requests_waiting",
        "vllm:num_requests_waiting",
    ],
    # Fraction (0-1) of the paged-KV block pool in TPU HBM that is in use.
    "kv_usage_perc": [
        "tpu:hbm_kv_usage_perc",
        "vllm:gpu_cache_usage_perc",
        "vllm:cpu_cache_usage_perc",
    ],
    # Rolling prefix-cache hit rate (0-1).
    "prefix_cache_hit_rate": [
        "tpu:prefix_cache_hit_rate",
        "vllm:gpu_prefix_cache_hit_rate",
    ],
    # Fraction of KV blocks currently offloaded to host DRAM.
    "kv_offload_usage_perc": [
        "tpu:host_kv_usage_perc",
    ],
    # TPU duty cycle (0-1), the TPU analogue of GPU utilization.
    "accelerator_utilization": [
        "tpu:duty_cycle",
    ],
    # Mean host-side serialization per decode step, ms (pipeline health).
    "decode_host_gap_ms": [
        "tpu:decode_host_gap_ms",
    ],
    # Prompt tokens queued in waiting+preempted sequences (the disagg
    # policy's prefill-pool selection signal).
    "queued_prompt_tokens": [
        "tpu:queued_prompt_tokens",
    ],
    # Cumulative engine-side admission 429s.  The fleet capacity model
    # (router/capacity.py) treats a GROWING value as saturation evidence
    # even when another router instance absorbed the 429s.
    "admission_rejected_total": [
        "tpu:admission_rejected_total",
    ],
    # Prefix-cache truth counters/size.  The router's fleet popularity
    # view (routing/kv_aware.py) computes the fleet-wide KV hit rate
    # from the hit/query token counters and reconciles its prefix-owner
    # map against the cached-blocks gauge: a collapse to ~0 means the
    # engine restarted and every "resident" prefix there is gone.
    "prefix_cache_hit_tokens": [
        "tpu:prefix_cache_hit_tokens_total",
    ],
    "prefix_cache_query_tokens": [
        "tpu:prefix_cache_query_tokens_total",
    ],
    "prefix_cache_blocks": [
        "tpu:prefix_cache_blocks",
    ],
}

# Names our own engine exports (used by the engine server and the fake
# engine; keep in sync with ENGINE_METRIC_CANDIDATES above).
TPU_NUM_REQUESTS_RUNNING = "tpu:num_requests_running"
TPU_NUM_REQUESTS_WAITING = "tpu:num_requests_waiting"
TPU_HBM_KV_USAGE_PERC = "tpu:hbm_kv_usage_perc"
TPU_PREFIX_CACHE_HIT_RATE = "tpu:prefix_cache_hit_rate"
# Prefix-cache truth: cumulative matched/queried prompt tokens (counters
# — rates stay derivable after engine restarts, unlike the rolling-ratio
# gauge above) and content-valid blocks resident right now (gauge — the
# cache SIZE the router's popularity view reconciles owner maps against).
TPU_PREFIX_CACHE_HIT_TOKENS = "tpu:prefix_cache_hit_tokens_total"
TPU_PREFIX_CACHE_QUERY_TOKENS = "tpu:prefix_cache_query_tokens_total"
TPU_PREFIX_CACHE_BLOCKS = "tpu:prefix_cache_blocks"
TPU_HOST_KV_USAGE_PERC = "tpu:host_kv_usage_perc"
TPU_DUTY_CYCLE = "tpu:duty_cycle"
TPU_LOADED_LORAS = "tpu:loaded_loras"
# Mean host-side serialization per decode step, ms: time the accelerator
# sat idle between decode steps waiting on host work.  ≈0 when the
# engine's one-step-lookahead decode pipeline is active.
TPU_DECODE_HOST_GAP_MS = "tpu:decode_host_gap_ms"

# Remote-prefix prefetches currently in flight on the async KV transfer
# plane (gauge; a persistently high value beside a low hit rate means the
# store is slower than admission).
TPU_KV_PREFETCH_INFLIGHT = "tpu:kv_prefetch_inflight"

# Step-loop watchdog (gauge): seconds since the engine step thread last
# started an iteration.  A hung device dispatch stops it advancing; the
# engine's /health fails liveness past scheduler.step_watchdog_s, so k8s
# restarts a wedged engine instead of probing it green forever.
TPU_LAST_STEP_AGE = "tpu:last_step_age_seconds"
# Prompt tokens held by waiting+preempted sequences (gauge): the queue
# depth bounded admission enforces, in tokens.
TPU_QUEUED_PROMPT_TOKENS = "tpu:queued_prompt_tokens"

# The custom metric the prometheus-adapter exposes for HPA (reference:
# observability/prom-adapter.yaml:8-20 exposes vllm:num_requests_waiting).
HPA_QUEUE_METRIC = TPU_NUM_REQUESTS_WAITING

# Engine counters (monotonic; everything else above is a gauge).
TPU_TOTAL_PROMPT_TOKENS = "tpu:total_prompt_tokens"
TPU_TOTAL_GENERATED_TOKENS = "tpu:total_generated_tokens"
TPU_TOTAL_FINISHED_REQUESTS = "tpu:total_finished_requests"
TPU_NUM_PREEMPTIONS = "tpu:num_preemptions"
# Cross-engine prefix sharing (cache.disagg_role): blocks imported from /
# pushed to the shared store.
TPU_REMOTE_PREFIX_BLOCKS_FETCHED = "tpu:remote_prefix_blocks_fetched"
TPU_REMOTE_PREFIX_BLOCKS_EXPORTED = "tpu:remote_prefix_blocks_exported"
# N-gram speculative decoding effectiveness (acceptance rate =
# accepted/drafted; a low rate means the drafter wastes verify FLOPs).
TPU_SPEC_TOKENS_DRAFTED = "tpu:spec_tokens_drafted"
TPU_SPEC_TOKENS_ACCEPTED = "tpu:spec_tokens_accepted"
# Prompt tokens prefilled inside fused mixed decode+prefill steps
# (scheduler mixed_batch): nonzero means arriving prompts are chunking
# alongside live decodes instead of stalling them (the prefill/decode
# interference signal, read beside tpu:itl_seconds).
TPU_PREFILL_CHUNK_TOKENS = "tpu:prefill_chunk_tokens"
# Async KV transfer plane (kv/prefetch.py): blocks imported into the
# prefix cache by admission-time remote prefetch (hit) vs fetched and
# then dropped unused — cancelled, malformed, or undeliverable (waste).
# hit/(hit+waste) is the prefetch efficiency; read beside
# tpu:remote_kv_fetch_seconds for the latency the plane is hiding.
TPU_KV_PREFETCH_HIT = "tpu:kv_prefetch_hit"
TPU_KV_PREFETCH_WASTE = "tpu:kv_prefetch_waste"
# Overload protection (docs/robustness.md): requests shed by bounded
# admission with a structured 429, and requests shed/aborted because
# their client deadline expired before first token.
TPU_ADMISSION_REJECTED = "tpu:admission_rejected_total"
TPU_DEADLINE_EXPIRED = "tpu:deadline_expired_total"
# Fused speculative windows (scheduler speculative_ngram or
# speculative_model with the K-step window active): per-window outcome
# split of the on-device draft-and-verify — draft tokens the verifier
# accepted / rejected inside windows, plus window tokens emitted by the
# fused path but undeliverable at collect (abort / out-of-band finish
# mid-window) — split by the proposal source (drafter: ngram — prompt
# lookup from the carried history buffer; model — the tiny draft model
# riding the scan).  Acceptance RATE per drafter is accepted /
# (accepted + rejected) over this family; the unlabeled totals stay
# derivable from tpu:spec_tokens_{drafted,accepted}, which the fused
# path feeds alongside the legacy host path.
TPU_SPEC_WINDOW_TOKENS = "tpu:spec_window_tokens_total"
# The closed outcome and drafter sets, pre-seeded as zero-valued series
# so scrapers, dashboards, and rate() see stable label sets from boot.
TPU_SPEC_WINDOW_OUTCOMES = ("accepted", "rejected", "wasted")
TPU_SPEC_WINDOW_DRAFTERS = ("ngram", "model")
# Scan wall-time attributed to the draft model's forwards inside fused
# speculative windows (static cost-model split of the collect wait) —
# the overhead the model drafter's acceptance rate must out-earn.  The
# ngram drafter accrues ZERO here (its lookup is a gather, not a
# forward); compare rate() against tpu:spec_window_tokens_total
# {outcome="accepted",drafter="model"} for the speculation ROI.
TPU_SPEC_DRAFT_FRACTION_SECONDS = "tpu:spec_draft_fraction_seconds"
# K-step decode windows (scheduler multi_step_window): dispatches that
# fell back to single-step because a co-scheduled request needed
# host-sampled features (labeled by reason — logprobs / logit_bias /
# guided; one such request de-optimizes every co-scheduled stream) or
# because a waiting prompt forced K=1 admission cadence and the mixed
# K-step window could not serve it (waiting_head — with mixed windows
# on and chunkable traffic this series should sit at ZERO under load;
# a climbing rate means sustained arrivals are forfeiting the window
# amortization), and window tokens emitted but undeliverable (sequence
# aborted or finished out-of-band while the window flew; ordinary stops
# cost zero under the device stop-mask).  waste/total_generated is the
# amortization tax.
TPU_MULTISTEP_FALLBACK = "tpu:multistep_fallback_total"
# The closed reason set, pre-seeded as zero-valued series so scrapers,
# dashboards, and rate() see stable label sets from boot.  The mixed-
# window decline reasons are split so the flight recorder (and this
# family) can say WHY a waiting prompt forced K=1: bucket_mismatch — the
# head chunk fit no static chunk bucket; pool_pressure — the KV pool had
# no room for the chunk's blocks; waiting_head — the residual decline
# (mixed windows disabled, or an unpackable final chunk); draft_pool —
# the draft model's dedicated KV pool could not cover the batch, so the
# window ran plain (non-speculative) instead.
TPU_MULTISTEP_FALLBACK_REASONS = (
    "guided", "logit_bias", "logprobs", "waiting_head",
    "bucket_mismatch", "pool_pressure", "draft_pool",
)
TPU_MULTISTEP_WASTED_TOKENS = "tpu:multistep_wasted_tokens_total"
# Mixed K-step windows (scheduler mixed_window): prompt tokens whose
# prefill chunks rode the device-resident decode scan — the subset of
# tpu:prefill_chunk_tokens that did NOT pay a per-chunk host
# round-trip.  Its ratio to tpu:prefill_chunk_tokens is the window
# coverage of sustained-arrival prefill traffic.
TPU_MIXED_WINDOW_CHUNK_TOKENS = "tpu:mixed_window_chunk_tokens_total"
# Packed multi-prompt windows (scheduler multi_prompt_window): distinct
# prompts whose chunks rode EACH mixed K-step window, as a histogram —
# the packing depth.  A mass at bucket 1 under queue depth means the
# packed path is not engaging (flag off, or per-window admission
# declining); mass in the >1 buckets is queue depth being converted
# into device utilization.
TPU_MIXED_WINDOW_PROMPTS = "tpu:mixed_window_prompts_per_window"
# Batched encode lane (scheduler encode_lane; docs/engine.md "The encode
# lane"): texts embedded via the step thread's [B, T]-bucketed encode
# batches (counter), the queue of texts the batcher is carrying (gauge —
# the depth encode admission bounds), per-batch ACTUAL size as a
# histogram (mass near the top bucket means embed/rerank/score traffic
# is coalescing; mass stuck at 1 under load means it arrives too sparse
# to batch and is paying per-text dispatches), and per-batch wall
# seconds including the device sync.
TPU_ENCODE_TEXTS = "tpu:encode_texts_total"
TPU_ENCODE_QUEUE_DEPTH = "tpu:encode_queue_depth"
TPU_ENCODE_BATCH_SIZE = "tpu:encode_batch_size"
TPU_ENCODE_SECONDS = "tpu:encode_seconds"
# Seconds of host<->device transfer work issued while the device was
# BUSY with an in-flight window — H2D chunk staging for chained windows
# and D2H offload gathers dispatched under the scan.  Each second here
# is a stall the overlap-everything dispatch avoided; compare its rate
# to wall time for the overlap duty-cycle.
TPU_WINDOW_TRANSFER_OVERLAP_SECONDS = (
    "tpu:window_transfer_overlap_seconds_total"
)
# Disaggregated prefill/decode serving (docs/engine.md "Disaggregated
# data path"): prefill-phase prime completions served (the handoff
# producer side), and decode-phase handoff prefetch outcomes — a hit
# means the imported chain covered the whole prompt (decode executed no
# prompt tokens), a miss means the decode engine recomputed the prefill
# locally (the in-place fused fallback; reads beside
# tpu_router:disagg_fallback_total{reason="prefix_miss"}).
TPU_DISAGG_PREFILL_PRIMES = "tpu:disagg_prefill_primes_total"
TPU_DISAGG_HANDOFF_HITS = "tpu:disagg_handoff_hits_total"
TPU_DISAGG_HANDOFF_MISSES = "tpu:disagg_handoff_misses_total"
# Quantized KV tiering plane (engine/kv/quant.py, kvserver/protocol.py
# serde versioning): bytes crossing each tier boundary (tier ∈ host /
# remote) by wire representation (format ∈ dense / int8 — int8 is the
# native (data, scale) quantized wire, dense the legacy fp32/model-dtype
# wire), and KV snapshots encoded onto the kvserver wire by serde
# version (v1 = untagged dense, v2 = tagged quantized).  A quantized-
# cache fleet stuck on {format="dense"} / {version="v1"} means the
# store never advertised serde v2 — the rollout is incomplete and every
# offload/export is paying the retired 4x fp32 byte tax.
TPU_KV_WIRE_BYTES = "tpu:kv_wire_bytes_total"
TPU_KV_WIRE_TIERS = ("host", "remote")
TPU_KV_WIRE_FORMATS = ("dense", "int8")
TPU_KV_SNAPSHOT_FORMAT = "tpu:kv_snapshot_format_total"
TPU_KV_SNAPSHOT_VERSIONS = ("v1", "v2")
# Slice-coherent lifecycle (multi-host lockstep groups; docs/robustness.md
# "Slice lifecycle contract").  The leader exports group liveness truth:
# per-member seconds since the last lockstep ack advanced (a member
# frozen near --slice-member-timeout-s is about to fail the slice),
# the group epoch (leader boot nonce — strictly larger after every group
# restart, so a flat line that steps is a restart marker), member
# failures by reason, and follower->leader drain relays (preStop/SIGTERM
# on a follower drains the WHOLE slice through the leader).
TPU_LOCKSTEP_MEMBER_LAST_ACK = "tpu:lockstep_member_last_ack_seconds"
TPU_LOCKSTEP_GROUP_EPOCH = "tpu:lockstep_group_epoch"
TPU_LOCKSTEP_MEMBER_FAILURES = "tpu:lockstep_member_failures_total"
# The closed reason set, pre-seeded as zero-valued series so scrapers,
# dashboards, and rate() see stable label sets from boot.
TPU_LOCKSTEP_FAILURE_REASONS = ("member_silent", "epoch_mismatch")
TPU_SLICE_DRAIN_RELAYS = "tpu:slice_drain_relays_total"
# XLA compile-event tracking (obs/compile_tracker.py): seconds spent in
# trace+compile per executable shape key (labeled counter — the label is
# the jit entry point plus a compact arg-shape signature), and the count
# of distinct executable keys compiled since boot (gauge; read against
# the config-derived inventory at GET /debug/compiles for warmup
# coverage).  A compile_seconds series growing under steady traffic
# means live shapes are still missing from warmup.
TPU_COMPILE_SECONDS = "tpu:compile_seconds_total"
TPU_COMPILED_SHAPES = "tpu:compiled_shapes"
# Trace-ring eviction truth (obs/trace.py byte bound): completed
# /debug/requests records dropped by the count or byte bound.  Nonzero
# under a long-prompt burst is EXPECTED (the bound doing its job);
# silent unbounded growth is what it replaces.
TPU_OBS_TRACE_DROPPED = "tpu:obs_trace_dropped_total"
TPU_COUNTERS = frozenset({
    TPU_PREFIX_CACHE_HIT_TOKENS,
    TPU_PREFIX_CACHE_QUERY_TOKENS,
    TPU_TOTAL_PROMPT_TOKENS,
    TPU_TOTAL_GENERATED_TOKENS,
    TPU_TOTAL_FINISHED_REQUESTS,
    TPU_NUM_PREEMPTIONS,
    TPU_REMOTE_PREFIX_BLOCKS_FETCHED,
    TPU_REMOTE_PREFIX_BLOCKS_EXPORTED,
    TPU_SPEC_TOKENS_DRAFTED,
    TPU_SPEC_TOKENS_ACCEPTED,
    TPU_SPEC_DRAFT_FRACTION_SECONDS,
    TPU_PREFILL_CHUNK_TOKENS,
    TPU_KV_PREFETCH_HIT,
    TPU_KV_PREFETCH_WASTE,
    TPU_ADMISSION_REJECTED,
    TPU_DEADLINE_EXPIRED,
    TPU_MULTISTEP_WASTED_TOKENS,
    TPU_MIXED_WINDOW_CHUNK_TOKENS,
    TPU_ENCODE_TEXTS,
    TPU_WINDOW_TRANSFER_OVERLAP_SECONDS,
    TPU_DISAGG_PREFILL_PRIMES,
    TPU_DISAGG_HANDOFF_HITS,
    TPU_DISAGG_HANDOFF_MISSES,
    TPU_SLICE_DRAIN_RELAYS,
    TPU_OBS_TRACE_DROPPED,
})


# -- latency histogram families (this PR's tracing layer) ------------------
#
# Every span duration the tracing subsystem records also feeds a Prometheus
# HISTOGRAM (p50/p95/p99 queryable via histogram_quantile) alongside the
# pre-existing gauges, which keep their names unchanged.

# Engine request-level families, keyed by obs.EngineObs.REQUEST_HISTS names
# (one observation per request — except itl, observed per token GAP, so
# its _count is ~tokens not requests; detokenize_time is the request's
# total accumulated host detokenize cost).
TPU_REQUEST_HISTOGRAMS = {
    "ttft": "tpu:ttft_seconds",
    "itl": "tpu:itl_seconds",
    "e2e_latency": "tpu:e2e_latency_seconds",
    "queue_time": "tpu:queue_time_seconds",
    "prefill_time": "tpu:prefill_time_seconds",
    "decode_time": "tpu:decode_time_seconds",
    "detokenize_time": "tpu:detokenize_time_seconds",
}

# Engine step-phase families, keyed by obs.EngineObs.STEP_PHASES names
# (one observation per engine step — unit-comparable across phases).
TPU_STEP_HISTOGRAMS = {
    "schedule": "tpu:step_schedule_seconds",
    "dispatch": "tpu:step_dispatch_seconds",
    "collect": "tpu:step_collect_seconds",
    "sample": "tpu:step_sample_seconds",
    # Fused mixed decode+prefill-chunk steps, end-to-end wall time per
    # step (its _count / all-step counts = fraction of steps a prompt
    # chunked alongside live decodes).
    "mixed": "tpu:step_mixed_seconds",
}

# Async KV transfer-plane families, keyed by obs.EngineObs.KV_PHASES
# names.  remote_kv_fetch is one observation per store round-trip (MGET
# chain fetch or restore GET, observed on the fetcher threads) — the
# network latency the plane hides from the step loop; offload_stage is
# one observation per staged preemption snapshot (device gather dispatch
# -> host copy complete, observed on the stager's writer thread).
TPU_KV_HISTOGRAMS = {
    "remote_kv_fetch": "tpu:remote_kv_fetch_seconds",
    "offload_stage": "tpu:offload_stage_seconds",
}

# Router families (labeled by backend server), fed by RequestStatsMonitor.
ROUTER_HISTOGRAMS = {
    "ttft": "tpu_router:ttft_seconds",
    "itl": "tpu_router:itl_seconds",
    "latency": "tpu_router:e2e_latency_seconds",
    "queueing": "tpu_router:request_queueing_seconds",
}


def render_prometheus(pairs) -> str:
    """Serialize (name, value) pairs in Prometheus text format with TYPE
    lines.  Shared by the real engine server and the fake engine so the
    observability contract cannot silently diverge between them."""
    lines = []
    for name, value in pairs:
        kind = "counter" if name in TPU_COUNTERS else "gauge"
        lines.append(f"# TYPE {name} {kind}")
        lines.append(f"{name} {float(value)}")
    return "\n".join(lines) + "\n"


def render_labeled_counter(name: str, label: str, values) -> str:
    """Serialize one LABELED counter family ({label="key"} series from a
    plain dict).  The TYPE header renders even with no series yet so
    scrapers and dashboards see a stable family name from boot (same
    contract render_prometheus gives unlabeled families).  Shared by the
    real engine server and the fake engine."""
    lines = [f"# TYPE {name} counter"]
    for key in sorted(values):
        lines.append(f'{name}{{{label}="{key}"}} {float(values[key])}')
    return "\n".join(lines) + "\n"


def render_labeled_gauge(name: str, label: str, values) -> str:
    """Serialize one LABELED gauge family ({label="key"} series from a
    plain dict) — the gauge sibling of render_labeled_counter, with the
    same stable-TYPE-header contract.  Shared by the real engine server
    and the fake engine."""
    lines = [f"# TYPE {name} gauge"]
    for key in sorted(values):
        lines.append(f'{name}{{{label}="{key}"}} {float(values[key])}')
    return "\n".join(lines) + "\n"


def render_labeled_counter2(name: str, labels, values) -> str:
    """Two-label sibling of render_labeled_counter: ``values`` maps
    (label1_value, label2_value) tuples to counts.  Same stable-TYPE-
    header contract; shared by the real engine server and the fake
    engine."""
    l1, l2 = labels
    lines = [f"# TYPE {name} counter"]
    for key in sorted(values):
        lines.append(
            f'{name}{{{l1}="{key[0]}",{l2}="{key[1]}"}} '
            f"{float(values[key])}"
        )
    return "\n".join(lines) + "\n"
