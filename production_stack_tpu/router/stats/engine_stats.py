"""Engine /metrics scraper.

Reference counterpart: src/vllm_router/stats/engine_stats.py:27-196
(EngineStats.from_vllm_scrape, EngineStatsScraper background thread).

Design deviation: the reference runs a thread with blocking ``requests`` GETs
(engine_stats.py:92-110); our router is a single-event-loop aiohttp app, so
the scraper is an asyncio task that fans out concurrent GETs to all engines —
one slow engine no longer delays the others' scrape freshness.  Metric names
are resolved through the shared vocabulary module (vocabulary.py) so the
router can front both our JAX engine (``tpu:*``) and stock vLLM (``vllm:*``).
"""

from __future__ import annotations

import asyncio
import dataclasses
import logging
import time
from typing import Dict, Optional

import aiohttp
from prometheus_client.parser import text_string_to_metric_families

from production_stack_tpu.router.stats.vocabulary import ENGINE_METRIC_CANDIDATES

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class EngineStats:
    """One engine's scraped gauges (canonical vocabulary)."""

    num_running_requests: int = 0
    num_queuing_requests: int = 0
    kv_usage_perc: float = 0.0
    prefix_cache_hit_rate: float = 0.0
    kv_offload_usage_perc: float = 0.0
    accelerator_utilization: float = 0.0
    decode_host_gap_ms: float = 0.0
    # Prompt tokens held by waiting+preempted sequences — the disagg
    # policy's prefill-pool load signal (prefill is prompt-token-bound,
    # so queue depth in requests under-weights long prompts).
    queued_prompt_tokens: float = 0.0
    # Cumulative engine admission 429s (counter): the capacity model
    # reads its growth as saturation evidence from OTHER routers' traffic.
    admission_rejected_total: float = 0.0
    # Prefix-cache truth (routing/kv_aware.py popularity view): matched /
    # queried prompt tokens since boot (counters — the fleet KV hit rate
    # is sum(hit)/sum(query) across backends) and content-valid blocks
    # resident right now (gauge — a collapse to ~0 between scrapes means
    # the engine restarted and its cache is empty, whatever the router's
    # owner map believes).
    prefix_cache_hit_tokens: float = 0.0
    prefix_cache_query_tokens: float = 0.0
    prefix_cache_blocks: float = 0.0
    scraped_at: float = 0.0

    # Sample-name suffixes that belong to histogram/summary internals.
    _SERIES_SUFFIXES = ("_bucket", "_sum", "_count", "_created")

    @classmethod
    def from_prometheus_text(cls, text: str, scraped_at: Optional[float] = None) -> "EngineStats":
        values: Dict[str, float] = {}
        for family in text_string_to_metric_families(text):
            # The engine now exports histogram families alongside its
            # gauges; their _bucket/_sum/_count samples must never enter
            # the scalar map — "last sample wins" would let a same-prefix
            # series shadow a real gauge.  Filter by family type AND
            # sample suffix (suffix alone also guards untyped expositions).
            if family.type in ("histogram", "summary"):
                continue
            for sample in family.samples:
                if sample.name.endswith(cls._SERIES_SUFFIXES):
                    continue
                # Last sample wins; engine gauges are unlabeled or
                # single-labeled per engine, either is fine for a scalar read.
                values[sample.name] = sample.value
        fields: Dict[str, float] = {}
        for field, candidates in ENGINE_METRIC_CANDIDATES.items():
            for name in candidates:
                # prometheus_client normalizes ':' in exposition names; check both.
                for probe in (name, name.replace(":", "_")):
                    if probe in values:
                        fields[field] = values[probe]
                        break
                else:
                    continue
                break
        stats = cls(scraped_at=scraped_at if scraped_at is not None else time.time())
        for field, value in fields.items():
            if field.startswith("num_"):
                setattr(stats, field, int(value))
            else:
                setattr(stats, field, float(value))
        return stats


class EngineStatsScraper:
    """Periodically scrapes every discovered engine's /metrics endpoint."""

    def __init__(
        self,
        service_discovery,
        scrape_interval: float = 10.0,
        request_timeout: float = 5.0,
    ):
        self.service_discovery = service_discovery
        self.scrape_interval = float(scrape_interval)
        self.request_timeout = float(request_timeout)
        self._stats: Dict[str, EngineStats] = {}
        self._unreachable: set = set()
        self._task: Optional[asyncio.Task] = None
        self._session: Optional[aiohttp.ClientSession] = None
        self._last_loop_at: float = 0.0

    async def start(self) -> None:
        if self._task is not None:
            return
        self._session = aiohttp.ClientSession(
            timeout=aiohttp.ClientTimeout(total=self.request_timeout)
        )
        self._last_loop_at = time.time()
        self._task = asyncio.create_task(self._run(), name="engine-stats-scraper")

    async def close(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except (asyncio.CancelledError, Exception):
                pass
            self._task = None
        if self._session is not None:
            await self._session.close()
            self._session = None

    async def _run(self) -> None:
        while True:
            try:
                await self.scrape_once()
            except Exception:
                logger.exception("engine stats scrape loop error")
            self._last_loop_at = time.time()
            await asyncio.sleep(self.scrape_interval)

    async def scrape_once(self) -> None:
        endpoints = self.service_discovery.get_endpoint_info()
        urls = [ep.url for ep in endpoints]
        results = await asyncio.gather(
            *(self._scrape_one(url) for url in urls), return_exceptions=True
        )
        fresh: Dict[str, EngineStats] = {}
        unreachable = set()
        for url, result in zip(urls, results):
            if isinstance(result, EngineStats):
                fresh[url] = result
            else:
                # Unreachable engines are dropped from stats so routing does
                # not consider them fresh (reference engine_stats.py:107-109),
                # and flagged so the request path can avoid them entirely
                # (improvement over the reference, which keeps round-robining
                # onto dead static backends).
                logger.warning("Failed to scrape %s/metrics: %s", url, result)
                unreachable.add(url)
        self._stats = fresh
        self._unreachable = unreachable

    async def _scrape_one(self, url: str) -> EngineStats:
        assert self._session is not None, "scraper not started"
        async with self._session.get(f"{url}/metrics") as resp:
            resp.raise_for_status()
            text = await resp.text()
        return EngineStats.from_prometheus_text(text)

    # -- read side (sync, called from request path) ------------------------

    def get_engine_stats(self) -> Dict[str, EngineStats]:
        return dict(self._stats)

    def get_unreachable_urls(self) -> set:
        """Engines whose last /metrics scrape failed (likely down)."""
        return set(self._unreachable)

    def get_health(self) -> bool:
        """Scrape loop is alive if it ticked within 3 intervals
        (reference composes this into /health, main_router.py:125-160)."""
        if self._task is None or self._task.done():
            return False
        return (time.time() - self._last_loop_at) < 3 * self.scrape_interval + 10
