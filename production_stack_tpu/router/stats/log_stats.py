"""Periodic human-readable stats dump.

Reference counterpart: src/vllm_router/stats/log_stats.py:21-82.  The
reference launches this with the wrong arity (app.py:222-225 passes one arg
to a two-arg function) so it crashes silently inside a daemon thread —
SURVEY.md section 7 bug list.  Here it is an asyncio task owned by the app's
cleanup context, so a crash is visible and cancellation is clean.
"""

from __future__ import annotations

import asyncio
import logging
import time

logger = logging.getLogger("production_stack_tpu.stats")


def format_stats_block(registry) -> str:
    from production_stack_tpu.router.service_discovery import DISCOVERY_SERVICE
    from production_stack_tpu.router.services.request_service.request import (
        ENGINE_STATS_SCRAPER,
        REQUEST_STATS_MONITOR,
    )

    lines = ["", "==================== Router Stats ===================="]
    discovery = registry.get(DISCOVERY_SERVICE)
    endpoints = discovery.get_endpoint_info() if discovery else []
    lines.append(f"Endpoints ({len(endpoints)}):")
    for ep in endpoints:
        lines.append(f"  {ep.url}  models={ep.model_names}")

    scraper = registry.get(ENGINE_STATS_SCRAPER)
    if scraper:
        for url, es in sorted(scraper.get_engine_stats().items()):
            lines.append(
                f"  [engine ] {url}: running={es.num_running_requests} "
                f"waiting={es.num_queuing_requests} kv={es.kv_usage_perc:.1%} "
                f"prefix_hit={es.prefix_cache_hit_rate:.1%} "
                f"host_gap={es.decode_host_gap_ms:.2f}ms"
            )
    monitor = registry.get(REQUEST_STATS_MONITOR)
    if monitor:
        # Tails alongside the means: averages hide p99 pain, so the dump
        # carries the histogram-state p95s (same state /metrics exports
        # as tpu_router:*_seconds histogram families).
        hists = monitor.get_histograms()
        for url, rs in sorted(monitor.get_request_stats(time.time()).items()):
            h = hists.get(url, {})
            p95_ttft = h["ttft"].quantile(0.95) if "ttft" in h else 0.0
            p95_itl = h["itl"].quantile(0.95) if "itl" in h else 0.0
            lines.append(
                f"  [request] {url}: qps={rs.qps:.2f} ttft={rs.ttft * 1e3:.1f}ms "
                f"p95_ttft={p95_ttft * 1e3:.1f}ms "
                f"latency={rs.latency:.2f}s itl={rs.itl * 1e3:.1f}ms "
                f"p95_itl={p95_itl * 1e3:.1f}ms "
                f"prefill={rs.in_prefill_requests} decode={rs.in_decoding_requests} "
                f"finished={rs.finished_requests}"
            )
    lines.append("======================================================")
    return "\n".join(lines)


async def log_stats_task(registry, interval: float = 10.0) -> None:
    while True:
        await asyncio.sleep(interval)
        try:
            logger.info(format_stats_block(registry))
        except Exception:
            logger.exception("stats logging failed")
