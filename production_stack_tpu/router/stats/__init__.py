"""Stats plane: engine scraping, request lifecycle windows, periodic logging.

Reference counterpart: src/vllm_router/stats/.
"""
