"""Router app assembly and entry point.

Reference counterpart: src/vllm_router/app.py:73-230 (lifespan,
initialize_all, main).  aiohttp instead of FastAPI/uvicorn; all singletons
live in a ServiceRegistry attached to the app.
"""

from __future__ import annotations

import asyncio
import logging
import uuid
from typing import Optional

import aiohttp
from aiohttp import web

from production_stack_tpu.obs.trace import Tracer
from production_stack_tpu.router import parser as router_parser
from production_stack_tpu.router.capacity import (
    CAPACITY_MODEL,
    FLEET_ADMISSION,
    CapacityModel,
    FleetAdmission,
)
from production_stack_tpu.router.circuit_breaker import CircuitBreaker
from production_stack_tpu.router.routing import initialize_routing_logic
from production_stack_tpu.router.service_discovery import (
    DISCOVERY_SERVICE,
    build_service_discovery,
)
from production_stack_tpu.router.services.request_service.request import (
    CIRCUIT_BREAKER,
    CLIENT_SESSION,
    ENGINE_STATS_SCRAPER,
    REQUEST_REWRITER,
    REQUEST_STATS_MONITOR,
    RETRY_BUDGET,
    ROUTER_TRACER,
)
from production_stack_tpu.router.services.request_service.rewriter import (
    get_request_rewriter,
)
from production_stack_tpu.router.stats.engine_stats import EngineStatsScraper
from production_stack_tpu.router.stats.log_stats import log_stats_task
from production_stack_tpu.router.stats.request_stats import RequestStatsMonitor
from production_stack_tpu.utils.drain import DRAIN_CONTROLLER, DrainController
from production_stack_tpu.utils.log import init_logger
from production_stack_tpu.utils.net import parse_static_aliases, set_ulimit
from production_stack_tpu.utils.registry import ServiceRegistry

logger = logging.getLogger(__name__)


def routing_kwargs_from_args(routing_logic: str, args) -> dict:
    """CLI flags -> routing-logic constructor kwargs, for the given
    logic.  Shared by boot (initialize_all) AND the dynamic-config
    watcher's routing reconfigure — a hot-reload that rebuilt the
    kv_aware/popularity router from library defaults would silently
    discard every tuned --kv-* knob."""
    kwargs: dict = {}
    if routing_logic == "session":
        kwargs["session_key"] = args.session_key
    if routing_logic in ("kv_aware", "kv_aware_popularity"):
        kwargs["load_tradeoff"] = args.kv_affinity_tradeoff
        kwargs["chunk_chars"] = args.kv_chunk_chars
    if routing_logic == "kv_aware_popularity":
        kwargs.update(
            hot_threshold=args.kv_popularity_hot_threshold,
            popularity_halflife_s=args.kv_popularity_halflife_s,
            max_replicas=args.kv_popularity_max_replicas,
            replica_ttl_s=args.kv_popularity_replica_ttl_s,
            hot_credit_cap=args.kv_popularity_hot_credit_cap,
        )
    return kwargs


def initialize_all(app: web.Application, args) -> ServiceRegistry:
    """Wire every service into the app registry
    (reference initialize_all, app.py:97-207)."""
    registry: ServiceRegistry = app["registry"]

    discovery = build_service_discovery(args)
    registry.set(DISCOVERY_SERVICE, discovery)

    monitor = RequestStatsMonitor(sliding_window_size=args.request_stats_window)
    registry.set(REQUEST_STATS_MONITOR, monitor)

    registry.set(
        ROUTER_TRACER,
        Tracer(
            "router",
            enabled=not args.no_tracing,
            ring_size=args.trace_ring_size,
            ring_bytes=args.trace_ring_bytes,
        ),
    )

    scraper = EngineStatsScraper(discovery, scrape_interval=args.engine_stats_interval)
    registry.set(ENGINE_STATS_SCRAPER, scraper)

    initialize_routing_logic(
        registry, args.routing_logic,
        **routing_kwargs_from_args(args.routing_logic, args),
    )

    aliases = parse_static_aliases(args.model_aliases) if args.model_aliases else None
    registry.set(REQUEST_REWRITER, get_request_rewriter(args.request_rewriter, aliases))

    # Overload protection + graceful lifecycle (docs/robustness.md).
    # Breaker disabled (--no-circuit-breaker) leaves the key unset, which
    # reproduces the pre-breaker proxy path exactly.
    if not args.no_circuit_breaker:
        registry.set(
            CIRCUIT_BREAKER,
            CircuitBreaker(
                failure_threshold=args.breaker_failure_threshold,
                open_base_s=args.breaker_open_s,
            ),
        )
    registry.set(RETRY_BUDGET, args.retry_budget)
    registry.set(DRAIN_CONTROLLER, DrainController(grace_s=args.drain_grace_s))

    # Fleet-level admission (router/capacity.py): capacity model +
    # admission controller.  --no-fleet-admission leaves BOTH keys unset,
    # reproducing the per-engine-shed-only path exactly (the capacity
    # model is only fed from the proxy/metrics paths through the keys).
    if not getattr(args, "no_fleet_admission", False):
        model = CapacityModel(
            default_slots=args.fleet_default_slots,
            slo_p95_itl_s=args.fleet_slo_p95_itl_s,
            slo_p95_ttft_s=args.fleet_slo_p95_ttft_s,
        )
        registry.set(CAPACITY_MODEL, model)
        registry.set(
            FLEET_ADMISSION,
            FleetAdmission(
                model,
                low_priority_headroom_frac=args.fleet_low_priority_headroom,
            ),
        )

    # Optional subsystems -------------------------------------------------
    if args.enable_batch_api:
        try:
            from production_stack_tpu.router.services.batch_service import (
                initialize_batch_service,
            )
        except ImportError as e:
            _unavailable("--enable-batch-api", e)
        initialize_batch_service(app, registry, args)

    if args.feature_gates:
        try:
            from production_stack_tpu.router.experimental import initialize_experimental
        except ImportError as e:
            _unavailable("--feature-gates", e)
        initialize_experimental(app, registry, args)

    # Encode-lane semantic cache (router/encode_cache.py): fronts the
    # embed/rerank/score proxy paths with chunk-hash-keyed exact replay
    # (+ the optional rerank similarity tier).  Composes with whatever
    # proxy_hooks the experimental tier installed above — the app has
    # ONE hooks slot, and the cache must see the request only if PII
    # screening didn't already block it.
    if getattr(args, "encode_cache_max_bytes", 0) > 0:
        from production_stack_tpu.router.encode_cache import (
            ENCODE_CACHE_SERVICE,
            ChainedProxyHooks,
            EncodeCache,
            EncodeCacheHooks,
            make_fleet_vectorizer,
        )

        encode_cache = EncodeCache(
            max_bytes=args.encode_cache_max_bytes,
            ttl_s=args.encode_cache_ttl_s,
            similarity_threshold=args.encode_cache_similarity_threshold,
            chunk_chars=args.kv_chunk_chars,
        )
        registry.set(ENCODE_CACHE_SERVICE, encode_cache)
        vectorize = (
            make_fleet_vectorizer(registry, chunk_chars=args.kv_chunk_chars)
            if args.encode_cache_similarity_threshold > 0 else None
        )
        cache_hooks = EncodeCacheHooks(encode_cache, vectorize=vectorize)
        prior = app.get("proxy_hooks")
        app["proxy_hooks"] = (
            ChainedProxyHooks(prior, cache_hooks) if prior is not None
            else cache_hooks
        )
        logger.info(
            "Encode-lane semantic cache enabled (max_bytes=%d, ttl=%.0fs, "
            "similarity=%.2f)",
            args.encode_cache_max_bytes, args.encode_cache_ttl_s,
            args.encode_cache_similarity_threshold,
        )

    if args.dynamic_config_json:
        try:
            from production_stack_tpu.router.dynamic_config import DynamicConfigWatcher
        except ImportError as e:
            _unavailable("--dynamic-config-json", e)
        registry.set(
            "dynamic_config_watcher",
            DynamicConfigWatcher(args.dynamic_config_json, registry, args),
        )

    return registry


def _unavailable(feature: str, exc: ImportError):
    raise SystemExit(
        f"{feature} is not available in this build: {exc}. "
        "See SURVEY.md section 7 for the build plan."
    )


def _is_data_plane(request: web.Request) -> bool:
    """POSTed model-serving work (the streams a drain must not accept
    more of); GET control-plane surfaces (/health, /metrics, /debug...)
    and POST /drain itself stay served throughout."""
    return request.method == "POST" and (
        request.path.startswith("/v1/")
        or request.path in ("/rerank", "/score", "/tokenize", "/detokenize")
    )


@web.middleware
async def drain_middleware(request: web.Request, handler):
    """Graceful lifecycle: reject new data-plane work with 503 +
    Connection: close while draining, and count in-flight data-plane
    requests so the drain knows when the last stream finished."""
    drain = request.app["registry"].get(DRAIN_CONTROLLER)
    if drain is None or not _is_data_plane(request):
        return await handler(request)
    if drain.draining:
        resp = web.json_response(
            {"error": {"message": "router is draining for shutdown",
                       "type": "shutting_down", "code": 503}},
            status=503,
        )
        resp.force_close()
        return resp
    drain.inc()
    try:
        return await handler(request)
    finally:
        drain.dec()


@web.middleware
async def request_id_middleware(request: web.Request, handler):
    """Honor an inbound X-Request-Id (mint one otherwise) and echo it on
    EVERY response — success, error, and aiohttp HTTPException paths.
    Streaming responses are prepared inside the proxy handler, so that
    path stamps the header itself before prepare(); this middleware covers
    everything else."""
    request_id = request.headers.get("x-request-id") or f"req-{uuid.uuid4().hex[:16]}"
    request["request_id"] = request_id
    try:
        response = await handler(request)
    except web.HTTPException as exc:
        exc.headers["X-Request-Id"] = request_id
        raise
    if not response.prepared:
        response.headers["X-Request-Id"] = request_id
    return response


def build_app(args, registry: Optional[ServiceRegistry] = None) -> web.Application:
    app = web.Application(middlewares=[request_id_middleware, drain_middleware])
    app["registry"] = registry if registry is not None else ServiceRegistry()
    app["args"] = args
    initialize_all(app, args)

    from production_stack_tpu.router.routers import (
        debug_router,
        main_router,
        metrics_router,
    )

    app.add_routes(main_router.routes)
    app.add_routes(metrics_router.routes)
    app.add_routes(debug_router.routes)
    if args.enable_batch_api:
        from production_stack_tpu.router.routers import batches_router, files_router

        app.add_routes(files_router.routes)
        app.add_routes(batches_router.routes)

    app.cleanup_ctx.append(_lifespan(args))
    return app


def _lifespan(args):
    """Startup/shutdown of background services
    (reference FastAPI lifespan, app.py:73-94)."""

    async def ctx(app: web.Application):
        registry: ServiceRegistry = app["registry"]
        # total=None: streamed responses legitimately run for minutes.
        # sock_read bounds the gap BETWEEN reads instead: a stalled engine
        # stream (no chunk for --stream-idle-timeout-s) is torn down and
        # the teardown propagates to the engine as a disconnect-abort,
        # instead of leaking the stream (and its engine-side sequence)
        # forever.
        idle = args.stream_idle_timeout_s
        session = aiohttp.ClientSession(
            timeout=aiohttp.ClientTimeout(
                total=None, sock_connect=30,
                sock_read=idle if idle and idle > 0 else None,
            ),
            connector=aiohttp.TCPConnector(limit=0),
        )
        registry.set(CLIENT_SESSION, session)

        discovery = registry.require(DISCOVERY_SERVICE)
        await discovery.start()

        scraper = registry.require(ENGINE_STATS_SCRAPER)
        await scraper.start()
        # Populate engine stats before serving the first request.
        try:
            await scraper.scrape_once()
        except Exception:
            logger.warning("initial engine-stats scrape failed", exc_info=True)

        watcher = registry.get("dynamic_config_watcher")
        if watcher is not None:
            await watcher.start()

        batch_processor = registry.get("batch_processor")
        if batch_processor is not None:
            await batch_processor.start()

        log_task = None
        if args.log_stats:
            log_task = asyncio.create_task(
                log_stats_task(registry, args.log_stats_interval)
            )

        yield

        if log_task is not None:
            log_task.cancel()
        if batch_processor is not None:
            await batch_processor.close()
        if watcher is not None:
            await watcher.close()
        await scraper.close()
        await discovery.close()
        await session.close()
        # Bounded sweep for anything still registered with a close()
        # (dynamically added services, experimental subsystems): each gets
        # at most the remaining grace instead of hanging shutdown
        # (utils/registry.py close contract).
        await registry.close(grace_s=args.drain_grace_s)

    return ctx


def main(argv=None) -> None:
    args = router_parser.parse_args(argv)
    init_logger("production_stack_tpu", args.log_level)
    set_ulimit()
    app = build_app(args)

    # Graceful SIGTERM (k8s pod termination): drain instead of aiohttp's
    # immediate GracefulExit — /ready flips to 503, new data-plane work is
    # rejected, in-flight streams finish within --drain-grace-s, then the
    # drain's exit_cb re-enters aiohttp's graceful-exit path via SIGINT
    # (cleanup_ctx still runs; exit code 0).  on_startup runs after
    # AppRunner.setup registered aiohttp's handlers, so this wins SIGTERM.
    import os
    import signal

    async def _install_sigterm(app_: web.Application) -> None:
        drain = app_["registry"].get(DRAIN_CONTROLLER)
        if drain is None:
            return
        drain.exit_cb = lambda: os.kill(os.getpid(), signal.SIGINT)
        loop = asyncio.get_running_loop()
        try:
            loop.add_signal_handler(
                signal.SIGTERM,
                lambda: (
                    logger.info("SIGTERM: beginning graceful drain"),
                    drain.begin(),
                ),
            )
        except (NotImplementedError, RuntimeError):
            pass

    app.on_startup.append(_install_sigterm)
    logger.info("Starting tpu-router on %s:%d", args.host, args.port)
    web.run_app(app, host=args.host, port=args.port, access_log=None)


if __name__ == "__main__":
    main()
