"""Online fleet capacity model + router-level (fleet) admission control.

ROADMAP item 2, the "millions of users" gap: PR 5 bounded each ENGINE's
queue (structured 429s once `max_queued_requests`/`max_queued_tokens`
trips), but nothing protected the FLEET — an overloaded fleet still
queues per-engine until every backend's local bound trips, paying a full
routing decision, backend connect, and engine admission pass per doomed
request.  This module makes the router the overload firewall: it learns
each backend's capacity online from the existing stats plane (the
engine-stats scraper + the request-stats monitor — no new probes) and
sheds at the router the moment estimated fleet headroom is exhausted, so
fleet-level sheds strictly precede engine-level 429s in an overload.

Capacity model (per backend, all observations from the stats plane):

* ``slots`` — the learned maximum USEFUL concurrency: how many requests
  this backend can hold in flight before it starts queueing (the engine's
  ``max_num_seqs`` analogue as observed from outside).  Starts at an
  optimistic prior (``default_slots``) and is clamped DOWN whenever the
  scrape shows the engine queueing (``tpu:num_requests_waiting`` > 0 or a
  growing ``tpu:queued_prompt_tokens``) or its windowed p95 ITL/TTFT
  breaches the SLO at the router-observed concurrency; it is probed back
  UP (one slot at a time) while the backend runs healthy at the frontier,
  so a transient brownout does not depress the estimate forever.
* ``qps_capacity`` — the admitted-QPS knee of the (admitted-QPS,
  p95-ITL/TTFT) curve: the highest windowed QPS this backend sustained
  while inside the SLO, shrunk proportionally (``qps * slo/p95``) when
  the SLO is breached.  Exported for scoring/HPA dashboards; admission
  itself keys on slots (concurrency is synchronously known at the router
  — no scrape/window lag on the shed decision).
* an engine 429 is a ZERO-HEADROOM observation: the backend told us its
  bound.  ``on_backpressure`` clamps slots to the observed concurrency
  and marks the backend saturated for the advertised ``Retry-After``
  window — the same event PR 5 uses to drop routing weight now also
  teaches the capacity model (docs/robustness.md "Fleet admission").

Headroom is measured in request SLOTS (spare concurrency), per pool:
with disagg role pools (PR 9) the prefill and decode pools have separate
headroom, and admission for a generation request keys on the
DECODE-CAPABLE pool only — a saturated prefill pool must not shed work
the decode/fused pool could absorb (the disagg policy already degrades
the prime phase to the fused path; shedding here would turn a degraded
request into a lost one).

Priority-aware degradation: requests carrying an OpenAI-style body
``priority`` > 0 (lower value = more important, matching the engine
scheduler's convention) or an ``x-request-priority`` header are
DEGRADABLE — they shed first, while fleet headroom is merely LOW
(below ``low_priority_headroom_frac`` of fleet slots), so speculative /
batch work drains off before interactive traffic feels anything.

Single-event-loop use only (the router is one asyncio loop): no locking,
mutating entry points are all called from request handlers or the
metrics endpoint.  Every threshold takes an injectable clock so tests
drive the model deterministically.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Dict, List, Mapping, Optional, Tuple

CAPACITY_MODEL = "capacity_model"
FLEET_ADMISSION = "fleet_admission"

# Closed reason set for tpu_router:fleet_admission_rejected_total — kept
# stable so dashboards and rate() see the same label sets from boot.
FLEET_SHED_REASONS = ("no_headroom", "low_priority")


def request_priority(headers: Mapping[str, str], body: Optional[dict]) -> int:
    """Effective request priority: the ``x-request-priority`` header wins,
    else the OpenAI-style body ``priority`` int (engine convention: lower
    = more important, 0 default; > 0 = degradable/speculative work)."""
    raw = headers.get("x-request-priority")
    if raw is None and body is not None:
        raw = body.get("priority")
    if raw is None:
        return 0
    try:
        return int(raw)
    except (TypeError, ValueError):
        return 0


@dataclasses.dataclass
class BackendCapacity:
    """One backend's learned capacity state."""

    slots: float                 # max useful concurrency estimate
    qps_capacity: float = 0.0    # admitted-QPS knee estimate
    saturated_until: float = 0.0  # zero-headroom window (engine 429)
    last_inflight: int = 0
    last_qps: float = 0.0
    last_p95_itl: float = 0.0
    last_p95_ttft: float = 0.0
    last_queued: float = 0.0
    last_queued_tokens: float = 0.0
    observations: int = 0

    def saturated(self, now: float) -> bool:
        return now < self.saturated_until


class CapacityModel:
    """Per-backend capacity estimates learned from the stats plane."""

    def __init__(
        self,
        *,
        default_slots: float = 64.0,
        min_slots: float = 1.0,
        slo_p95_itl_s: float = 2.0,
        slo_p95_ttft_s: float = 10.0,
        probe_step: float = 1.0,
        refresh_interval_s: float = 0.25,
        clock=time.time,
    ):
        if default_slots < min_slots:
            raise ValueError("default_slots must be >= min_slots")
        self.default_slots = float(default_slots)
        self.min_slots = float(min_slots)
        self.slo_p95_itl_s = float(slo_p95_itl_s)
        self.slo_p95_ttft_s = float(slo_p95_ttft_s)
        self.probe_step = float(probe_step)
        self.refresh_interval_s = float(refresh_interval_s)
        self._clock = clock
        self._backends: Dict[str, BackendCapacity] = {}
        self._last_refresh: float = 0.0
        # Last scraped engine-shed counter per url: growth between
        # refreshes is saturation evidence even when another router
        # instance absorbed the 429s (multi-router deployments).
        self._last_shed_counter: Dict[str, float] = {}

    # -- per-backend state -------------------------------------------------

    def _bc(self, url: str) -> BackendCapacity:
        bc = self._backends.get(url)
        if bc is None:
            bc = self._backends[url] = BackendCapacity(slots=self.default_slots)
        return bc

    def observe(
        self,
        url: str,
        *,
        inflight: int,
        qps: float = 0.0,
        p95_itl: float = 0.0,
        p95_ttft: float = 0.0,
        queued_requests: float = 0.0,
        queued_prompt_tokens: float = 0.0,
    ) -> None:
        """One stats-plane observation for ``url``.  Saturation evidence
        (engine-side queueing, SLO breach) clamps the slot estimate DOWN
        to the observed concurrency; a healthy reading at the frontier
        probes it UP by one step."""
        bc = self._bc(url)
        bc.observations += 1
        bc.last_inflight = int(inflight)
        bc.last_qps = float(qps)
        bc.last_p95_itl = float(p95_itl)
        bc.last_p95_ttft = float(p95_ttft)
        bc.last_queued = float(queued_requests)
        bc.last_queued_tokens = float(queued_prompt_tokens)

        itl_breach = p95_itl > 0 and p95_itl > self.slo_p95_itl_s
        ttft_breach = p95_ttft > 0 and p95_ttft > self.slo_p95_ttft_s
        queueing = queued_requests > 0
        if queueing or itl_breach or ttft_breach:
            # The backend is at/above capacity at this concurrency.
            bc.slots = max(self.min_slots, min(bc.slots, float(max(inflight, 1))))
            if qps > 0 and itl_breach:
                # Shrink the QPS knee proportionally to the breach.
                shrunk = qps * self.slo_p95_itl_s / p95_itl
                bc.qps_capacity = (
                    min(bc.qps_capacity, shrunk) if bc.qps_capacity > 0
                    else shrunk
                )
        else:
            if qps > bc.qps_capacity:
                bc.qps_capacity = float(qps)
            if inflight >= bc.slots:
                # Healthy at the frontier: probe one slot up so a
                # transiently clamped backend can re-earn its capacity.
                bc.slots = min(
                    self.default_slots * 4.0, bc.slots + self.probe_step
                )

    def on_backpressure(
        self, url: str, retry_after_s: Optional[float], inflight: Optional[int] = None
    ) -> None:
        """An engine 429 seen by the proxy: a zero-headroom observation.
        Clamp slots to the concurrency the 429 was observed at and mark
        the backend saturated for the advertised window (the same window
        PR 5 uses for the routing-weight drop)."""
        bc = self._bc(url)
        at = inflight if inflight is not None else bc.last_inflight
        bc.slots = max(self.min_slots, min(bc.slots, float(max(at, 1))))
        window = retry_after_s if retry_after_s and retry_after_s > 0 else 1.0
        bc.saturated_until = self._clock() + float(window)

    def prune(self, live_urls) -> List[str]:
        """Drop state for backends that left discovery (pod churn);
        returns the removed urls so the metrics layer can retire their
        gauge labels (same contract as CircuitBreaker.prune)."""
        live = set(live_urls)
        gone = [u for u in self._backends if u not in live]
        for url in gone:
            del self._backends[url]
        for url in [u for u in self._last_shed_counter if u not in live]:
            del self._last_shed_counter[url]
        return gone

    # -- bulk refresh from the stats plane ---------------------------------

    def refresh(
        self, endpoints, engine_stats, request_stats, prune: bool = True
    ) -> List[str]:
        """Fold one scrape/monitor snapshot into the model, then (only
        with ``prune=True``, i.e. when ``endpoints`` is the FULL live
        discovery list — the /metrics path) drop departures (returned,
        for gauge-label retirement).  The request path passes its
        per-request CANDIDATE list, which excludes backpressured/broken
        backends — pruning against it would evict exactly the saturation
        state the model just learned."""
        for ep in endpoints:
            es = engine_stats.get(ep.url)
            rs = request_stats.get(ep.url)
            self.observe(
                ep.url,
                inflight=getattr(rs, "uncompleted_requests", 0) if rs else 0,
                qps=getattr(rs, "qps", 0.0) if rs else 0.0,
                p95_itl=getattr(rs, "itl_p95", 0.0) if rs else 0.0,
                p95_ttft=getattr(rs, "ttft_p95", 0.0) if rs else 0.0,
                queued_requests=(
                    getattr(es, "num_queuing_requests", 0) if es else 0.0
                ),
                queued_prompt_tokens=(
                    getattr(es, "queued_prompt_tokens", 0.0) if es else 0.0
                ),
            )
            # AFTER the observation (so the healthy-frontier probe-up
            # cannot undo the clamp): a grown engine-shed counter since
            # the last scrape is a zero-headroom observation even when a
            # DIFFERENT router absorbed the 429s.  The baseline is only
            # seeded from a REAL scrape (es present): recording 0.0 for
            # an unscraped backend would misread a long-lived engine's
            # cumulative counter as fresh 429s on the router's first
            # post-restart refresh and spuriously clamp the whole fleet.
            if es is not None:
                shed_counter = getattr(es, "admission_rejected_total", 0.0)
                prev = self._last_shed_counter.get(ep.url)
                if prev is not None and shed_counter > prev:
                    self.on_backpressure(
                        ep.url, None,
                        inflight=(
                            getattr(rs, "uncompleted_requests", 0) if rs else 0
                        ),
                    )
                self._last_shed_counter[ep.url] = shed_counter
        gone = self.prune([ep.url for ep in endpoints]) if prune else []
        self._last_refresh = self._clock()
        return gone

    def refresh_maybe(
        self, endpoints, engine_stats, request_stats, monitor=None
    ) -> None:
        """Rate-limited refresh for the request path: at most one full
        fold per ``refresh_interval_s`` — per-request cost stays O(1).
        When ``monitor`` is given, the windowed p95 quantiles are
        recomputed from it (the per-request ``request_stats`` map skips
        them to keep the routing hot path cheap)."""
        if self._clock() - self._last_refresh < self.refresh_interval_s:
            return
        if monitor is not None:
            request_stats = monitor.get_request_stats(
                self._clock(), with_quantiles=True
            )
        self.refresh(endpoints, engine_stats, request_stats, prune=False)

    # -- reads --------------------------------------------------------------

    def slots_of(self, url: str) -> float:
        bc = self._backends.get(url)
        return bc.slots if bc is not None else self.default_slots

    def qps_capacity_of(self, url: str) -> float:
        bc = self._backends.get(url)
        return bc.qps_capacity if bc is not None else 0.0

    def capacity_score(self, url: str, inflight: Optional[int] = None) -> float:
        """Free-capacity fraction in [0, 1]: 1 = idle, 0 = saturated
        (slots full, or inside an engine-429 Retry-After window).
        Never-observed backends score against the prior."""
        bc = self._backends.get(url)
        if bc is None:
            used = inflight if inflight is not None else 0
            return max(0.0, min(1.0, 1.0 - used / self.default_slots))
        if bc.saturated(self._clock()):
            return 0.0
        used = inflight if inflight is not None else bc.last_inflight
        if bc.slots <= 0:
            return 0.0
        return max(0.0, min(1.0, 1.0 - used / bc.slots))

    def backend_headroom(self, url: str, inflight: Optional[int] = None) -> float:
        """Spare request slots on one backend (0 while saturated).
        Never-observed backends count against the optimistic prior, but
        still net off the caller's synchronous in-flight count."""
        bc = self._backends.get(url)
        if bc is None:
            used = inflight if inflight is not None else 0
            return max(0.0, self.default_slots - used)
        if bc.saturated(self._clock()):
            return 0.0
        used = inflight if inflight is not None else bc.last_inflight
        return max(0.0, bc.slots - used)

    def pool_capacity(self, endpoints) -> float:
        return sum(self.slots_of(ep.url) for ep in endpoints)

    def pool_headroom(self, endpoints, request_stats=None) -> float:
        """Fleet/pool headroom in spare request slots.  When the caller
        passes the live ``request_stats`` map, in-flight counts come from
        it synchronously (no scrape lag on the shed decision)."""
        total = 0.0
        for ep in endpoints:
            inflight = None
            if request_stats is not None:
                rs = request_stats.get(ep.url)
                inflight = getattr(rs, "uncompleted_requests", 0) if rs else 0
            total += self.backend_headroom(ep.url, inflight)
        return total

    def min_retry_after(self, endpoints, default: float = 1.0) -> float:
        """Soonest saturation window expiry across the pool — the honest
        Retry-After for a fleet-level shed."""
        now = self._clock()
        waits = [
            bc.saturated_until - now
            for url, bc in self._backends.items()
            if any(ep.url == url for ep in endpoints) and bc.saturated(now)
        ]
        if not waits:
            return float(default)
        return max(0.1, min(min(waits), 30.0))

    def snapshot(self) -> Dict[str, BackendCapacity]:
        """url -> live BackendCapacity (metrics endpoint render)."""
        return dict(self._backends)


@dataclasses.dataclass
class ShedDecision:
    """A fleet-level shed: why, and how long the client should back off."""

    reason: str          # one of FLEET_SHED_REASONS
    retry_after_s: float
    pool: str            # "fleet" | "decode" | "prefill" | "encode"
    headroom: float
    capacity: float


class FleetAdmission:
    """The shed decision: admit, or 429 at the router.

    Per-role aware: with disagg role pools, a generation request is
    gated on the DECODE-CAPABLE pool's headroom (fused endpoints count —
    they can absorb the whole request), never on the prefill pool's —
    see module docstring.  Priority-aware: degradable requests
    (priority > 0) shed early while headroom is merely low.
    """

    def __init__(
        self,
        model: CapacityModel,
        *,
        low_priority_headroom_frac: float = 0.15,
        retry_after_default_s: float = 1.0,
        clock=time.time,
    ):
        self.model = model
        self.low_priority_headroom_frac = float(low_priority_headroom_frac)
        self.retry_after_default_s = float(retry_after_default_s)
        self._clock = clock

    def check(
        self,
        endpoints: List,
        engine_stats: Mapping,
        request_stats: Mapping,
        priority: int = 0,
        monitor=None,
        lane: str = "generate",
    ) -> Optional[ShedDecision]:
        """None = admit.  ``endpoints`` is the already-filtered candidate
        list for this request (model + health + breaker filtering done).
        ``lane`` selects which role pool's headroom gates the request:
        ``"generate"`` (completions traffic, the default) keys on the
        decode-capable pool; ``"encode"`` (embeddings / rerank / score)
        keys on the encode pool — dedicated ``encode``-role members plus
        fused role-less backends — so an embed burst sheds against ITS
        pool's knee and never eats the generation pool's headroom."""
        if not endpoints:
            return None  # nothing to protect; the routing layer will 503
        self.model.refresh_maybe(endpoints, engine_stats, request_stats, monitor)
        pool_name, pool = self._admission_pool(endpoints, lane)
        capacity = self.model.pool_capacity(pool)
        headroom = self.model.pool_headroom(pool, request_stats)
        if capacity <= 0:
            return None
        if headroom <= 0:
            return ShedDecision(
                reason="no_headroom",
                retry_after_s=self.model.min_retry_after(
                    pool, self.retry_after_default_s
                ),
                pool=pool_name, headroom=headroom, capacity=capacity,
            )
        if priority > 0 and headroom < capacity * self.low_priority_headroom_frac:
            # Degradation ladder: speculative / low-priority work drains
            # off while the fleet still has a sliver of headroom, so
            # interactive traffic never queues behind it.
            return ShedDecision(
                reason="low_priority",
                retry_after_s=self.retry_after_default_s,
                pool=pool_name, headroom=headroom, capacity=capacity,
            )
        return None

    @staticmethod
    def _admission_pool(endpoints, lane: str = "generate") -> Tuple[str, List]:
        """The pool whose headroom gates this request: the decode-capable
        endpoints when disagg roles are configured (prefill-pool
        saturation must not shed work the decode/fused pool could
        absorb), the whole fleet otherwise.  On the encode lane the gate
        is the encode pool — ``encode``-role members plus fused
        role-less backends (which serve both surfaces); if no such
        endpoints exist the lane degrades to fleet-wide headroom rather
        than shedding everything."""
        if any(getattr(ep, "role", None) for ep in endpoints):
            if lane == "encode":
                encode_capable = [
                    ep for ep in endpoints
                    if getattr(ep, "role", None) in (None, "", "encode")
                ]
                if encode_capable:
                    return "encode", encode_capable
                return "fleet", list(endpoints)
            decode_capable = [
                ep for ep in endpoints
                if getattr(ep, "role", None) not in ("prefill", "encode")
            ]
            if decode_capable:
                return "decode", decode_capable
        return "fleet", list(endpoints)
