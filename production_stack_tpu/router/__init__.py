"""OpenAI-compatible L7 router (reference counterpart: src/vllm_router/)."""
