"""Service discovery: which serving engines exist and what models they host.

Reference counterpart: src/vllm_router/service_discovery.py:24-337
(EndpointInfo, StaticServiceDiscovery, K8sServiceDiscovery,
reconfigure_service_discovery).

Two implementations:

* :class:`StaticServiceDiscovery` — fixed URL/model lists from the CLI.
* :class:`K8sServiceDiscovery` (k8s_discovery.py) — watches pods via the
  Kubernetes API (raw HTTPS; the heavyweight ``kubernetes`` client package is
  not required on TPU images).

Both are registered/replaced through the shared ServiceRegistry rather than
the reference's module-global singleton + lock dance
(service_discovery.py:270-337).
"""

from __future__ import annotations

import asyncio
import dataclasses
import logging
import time
from typing import List, Optional

import aiohttp

logger = logging.getLogger(__name__)

DISCOVERY_SERVICE = "service_discovery"

# Disaggregated serving roles (docs/engine.md "Disaggregated data path").
# A "prefill"-role engine runs the prime phase and exports prefix chains;
# a "decode"-role engine admits with remote-prefetch imports.  An
# "encode"-role engine serves only the embed/rerank/score lane
# (docs/router.md "Encode lanes & semantic cache").  Role-less endpoints
# are fused (serve everything, today's behavior).
ENGINE_ROLES = ("prefill", "decode", "encode")
# Pod label the helm chart stamps on role-pool engine pods and the
# router's k8s discovery reads back (--k8s-role-label; stackcheck SC707
# pins the chart<->flag agreement).
DEFAULT_ROLE_LABEL = "app.production-stack-tpu/role"


@dataclasses.dataclass
class EndpointInfo:
    """One serving-engine endpoint (reference service_discovery.py:24-33)."""

    url: str
    model_names: List[str]
    added_timestamp: float = dataclasses.field(default_factory=time.time)
    model_label: Optional[str] = None  # engine's modelSpec label (helm)
    pod_name: Optional[str] = None
    # "chat" | "completion" | "embeddings" | "rerank" | "score"
    model_types: Optional[List[str]] = None
    sleep: bool = False  # engine put to sleep by autoscaler; excluded from routing
    # Role-pool assignment: "prefill" | "decode" | "encode" | None (fused).
    role: Optional[str] = None


def role_pool(endpoints: List["EndpointInfo"], role: str) -> List["EndpointInfo"]:
    """Endpoints labeled with exactly ``role``."""
    return [ep for ep in endpoints if ep.role == role]


def decode_capable(endpoints: List["EndpointInfo"]) -> List["EndpointInfo"]:
    """Endpoints eligible to serve the decode/generation phase: everything
    except dedicated prefill-pool and encode-pool backends (role-less
    fused endpoints count — they decode today and keep decoding under
    disagg)."""
    return [ep for ep in endpoints if ep.role not in ("prefill", "encode")]


def encode_capable(endpoints: List["EndpointInfo"]) -> List["EndpointInfo"]:
    """Endpoints eligible for the embed/rerank/score lane: dedicated
    ``encode``-pool members plus role-less fused backends (which serve
    both surfaces) — the pool whose headroom gates encode admission
    (router/capacity.py)."""
    return [ep for ep in endpoints if ep.role in (None, "", "encode")]


def roles_configured(endpoints: List["EndpointInfo"]) -> bool:
    return any(ep.role for ep in endpoints)


class ServiceDiscovery:
    """Interface (reference service_discovery.py:36-61)."""

    def get_endpoint_info(self) -> List[EndpointInfo]:
        raise NotImplementedError

    def get_unhealthy_endpoint_hashes(self) -> List[str]:
        return []

    def get_health(self) -> bool:
        """Is the discovery mechanism itself alive?"""
        return True

    async def start(self) -> None:  # pragma: no cover - trivial
        return

    async def close(self) -> None:  # pragma: no cover - trivial
        return


class StaticServiceDiscovery(ServiceDiscovery):
    """Fixed endpoint list (reference service_discovery.py:64-82).

    If ``probe_models`` is set and a URL has no configured model list, the
    models are discovered by GETting ``<url>/v1/models`` once at startup
    (mirrors the reference's K8s model probe, service_discovery.py:131-155).
    """

    def __init__(
        self,
        urls: List[str],
        models: Optional[List[List[str]]] = None,
        model_labels: Optional[List[str]] = None,
        model_types: Optional[List[List[str]]] = None,
        roles: Optional[List[Optional[str]]] = None,
        probe_models: bool = False,
        probe_timeout: float = 5.0,
    ):
        models = models if models is not None else [[] for _ in urls]
        if len(urls) != len(models):
            raise ValueError(
                f"static URLs ({len(urls)}) and model lists ({len(models)}) differ in length"
            )
        if roles is not None:
            for role in roles:
                if role and role not in ENGINE_ROLES:
                    raise ValueError(
                        f"invalid backend role {role!r}; expected one of "
                        f"{ENGINE_ROLES} or empty (fused)"
                    )
        now = time.time()
        self._endpoints = [
            EndpointInfo(
                url=url,
                model_names=list(model_list),
                added_timestamp=now,
                model_label=(model_labels[i] if model_labels else None),
                model_types=(model_types[i] if model_types else None),
                role=(roles[i] or None) if roles else None,
            )
            for i, (url, model_list) in enumerate(zip(urls, models))
        ]
        self._probe_models = probe_models
        self._probe_timeout = probe_timeout

    async def start(self) -> None:
        if not self._probe_models:
            return
        timeout = aiohttp.ClientTimeout(total=self._probe_timeout)
        async with aiohttp.ClientSession(timeout=timeout) as session:
            await asyncio.gather(
                *(self._probe_one(session, ep) for ep in self._endpoints if not ep.model_names),
                return_exceptions=True,
            )

    async def _probe_one(self, session: aiohttp.ClientSession, ep: EndpointInfo) -> None:
        try:
            async with session.get(f"{ep.url}/v1/models") as resp:
                resp.raise_for_status()
                body = await resp.json()
            ep.model_names = [m["id"] for m in body.get("data", [])]
            logger.info("Probed %s -> models %s", ep.url, ep.model_names)
        except Exception as e:
            logger.warning("Model probe failed for %s: %s", ep.url, e)

    def get_endpoint_info(self) -> List[EndpointInfo]:
        return list(self._endpoints)


def build_service_discovery(args) -> ServiceDiscovery:
    """Build a discovery backend from (possibly merged) CLI args — the one
    construction path shared by app startup and dynamic reconfiguration,
    so hot reloads keep labels/types/probing behavior."""
    from production_stack_tpu.utils.net import parse_static_models, parse_static_urls

    if args.service_discovery == "static":
        urls = parse_static_urls(args.static_backends)
        if args.static_models:
            # ';' separates multiple models on one backend.
            models = [
                entry.split(";") for entry in parse_static_models(args.static_models)
            ]
        else:
            models = [[] for _ in urls]
        labels = (
            parse_static_models(args.static_model_labels)
            if args.static_model_labels
            else None
        )
        types = (
            [entry.split(";") for entry in parse_static_models(args.static_model_types)]
            if args.static_model_types
            else None
        )
        # Per-backend disagg roles ("prefill,decode," — empty = fused);
        # getattr: dynamic-config reloads may carry pre-roles namespaces.
        roles_raw = getattr(args, "static_backend_roles", None)
        roles = (
            [entry.strip() or None for entry in roles_raw.split(",")]
            if roles_raw
            else None
        )
        return StaticServiceDiscovery(
            urls,
            models,
            model_labels=labels,
            model_types=types,
            roles=roles,
            probe_models=args.static_probe_models,
        )
    if args.service_discovery == "k8s":
        from production_stack_tpu.router.k8s_discovery import K8sServiceDiscovery

        return K8sServiceDiscovery(
            namespace=args.k8s_namespace,
            port=args.k8s_port,
            label_selector=args.k8s_label_selector,
            role_label=getattr(args, "k8s_role_label", DEFAULT_ROLE_LABEL),
        )
    raise ValueError(f"Invalid service discovery type: {args.service_discovery}")


def get_service_discovery(registry) -> ServiceDiscovery:
    return registry.require(DISCOVERY_SERVICE)
