"""Encode-lane semantic cache: answer repeat embed/rerank/score requests
at the router with zero engine work.

ROADMAP "millions of users" economics, applied to the encode surface:
embedding traffic is dominated by REPEATS — the same documents re-chunked
by RAG pipelines, the same queries re-scored against the same corpora —
and every repeat costs a full `[B, T]` encode batch on an engine
(docs/engine.md "The encode lane").  This cache fronts the encode lane
(docs/router.md "Encode lanes & semantic cache") with two tiers:

* **Exact tier** — keyed on the PR-13 chunk-hash chain
  (routing/kv_aware.py): each text is digested as a chained blake2b walk
  over ``chunk_chars`` slices INCLUDING the partial tail (the routing
  chain stops at full chunks because it keys *prefix affinity*; a cache
  key must cover every byte or "abc" and "abcd" would collide).  A hit
  replays the stored response bytes verbatim — byte-identical to the
  answer the engine gave, so clients cannot distinguish cache from
  compute.
* **Similarity tier** (optional, ``similarity_threshold`` > 0) — for
  rerank requests whose DOCUMENT set is an exact chain match but whose
  query text drifted (rephrasings of the same question against the same
  corpus).  The query is vectorized through the embed lane itself (ONE
  text) and compared against the stored queries' vectors; a cosine match
  at/above the threshold serves the cached ranking — one encode forward
  instead of N+1.  Embeddings requests never use this tier: vectorizing
  the query costs exactly the forward a hit would save.

Bounded by ``max_bytes`` with LRU eviction and a TTL staleness bound.
Both bounds are enforced at store/lookup time on the event loop — the
router is one asyncio loop, no locking (router/capacity.py precedent).

Metrics: the cache reuses the ``tpu_router:semantic_cache_{hits,misses,
size}`` families declared by router/experimental (re-declaring a
prometheus timeseries raises; the registry help names both caches).  The
``x-encode-cache: hit|similar`` response header marks served hits.
"""

from __future__ import annotations

import hashlib
import json
import logging
import time
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Tuple

from aiohttp import web

logger = logging.getLogger(__name__)

ENCODE_CACHE_SERVICE = "encode_cache"

_REQ_KEY = "encode_cache_store_key"

# Paths the cache fronts (the router's encode lane surface —
# services/request_service/request.py ENCODE_PATHS).
_EMBED_PATH = "/v1/embeddings"
_RERANK_PATHS = ("/v1/rerank", "/rerank")
_SCORE_PATHS = ("/v1/score", "/score")


def chunk_chain_key(text: str, chunk_chars: int) -> str:
    """Chained blake2b digest over ``chunk_chars`` slices of ``text``,
    INCLUDING the partial tail — the exact-tier key primitive.  Matches
    the PR-13 routing chain (kv_aware._prefix_hashes) on full chunks and
    extends it over the remainder so the key covers every byte."""
    h = hashlib.blake2b(digest_size=8)
    for start in range(0, max(len(text), 1), max(chunk_chars, 1)):
        h.update(text[start : start + chunk_chars].encode("utf-8"))
    return h.hexdigest()


class EncodeCache:
    """Byte-bounded, TTL'd, LRU exact-response cache for the encode lane,
    plus the rerank similarity tier's (docs_key -> query vectors) index."""

    def __init__(
        self,
        *,
        max_bytes: int,
        ttl_s: float = 300.0,
        similarity_threshold: float = 0.0,
        chunk_chars: int = 1024,
        clock=time.time,
    ):
        if max_bytes <= 0:
            raise ValueError("max_bytes must be > 0 (0 disables the cache)")
        if ttl_s <= 0:
            raise ValueError("ttl_s must be > 0")
        if not 0.0 <= similarity_threshold <= 1.0:
            raise ValueError("similarity_threshold must be in [0, 1]")
        self.max_bytes = int(max_bytes)
        self.ttl_s = float(ttl_s)
        self.similarity_threshold = float(similarity_threshold)
        self.chunk_chars = int(chunk_chars)
        self._clock = clock
        # exact key -> (response_bytes, stored_at, docs_key|None, qvec|None)
        self._entries: "OrderedDict[str, tuple]" = OrderedDict()
        self._bytes = 0
        self.hits = 0
        self.similar_hits = 0
        self.misses = 0
        self.evictions = 0

    # -- keys ----------------------------------------------------------------

    def request_key(self, path: str, body: Dict[str, Any]) -> Optional[Tuple]:
        """(exact_key, docs_key, query_text) for a cacheable request, or
        None.  ``docs_key``/``query_text`` are non-None only for rerank
        (the similarity tier's join).  Streaming bodies and non-text
        inputs are uncacheable."""
        model = body.get("model")
        cc = self.chunk_chars
        if path == _EMBED_PATH:
            raw = body.get("input")
            texts = [raw] if isinstance(raw, str) else raw
            if not isinstance(texts, list) or not texts or not all(
                isinstance(t, str) for t in texts
            ):
                return None
            # encoding_format et al. change the response shape — fold
            # every non-input field into the key rather than enumerate.
            aux = json.dumps(
                {k: v for k, v in body.items() if k != "input"},
                sort_keys=True,
            )
            exact = self._digest(
                path, str(model), aux, *[chunk_chain_key(t, cc) for t in texts]
            )
            return exact, None, None
        if path in _RERANK_PATHS:
            query, documents = body.get("query"), body.get("documents")
            if not isinstance(query, str) or not isinstance(documents, list) \
                    or not all(isinstance(d, str) for d in documents):
                return None
            aux = json.dumps(
                {k: v for k, v in body.items()
                 if k not in ("query", "documents")},
                sort_keys=True,
            )
            docs_key = self._digest(
                "rerank-docs", str(model), aux,
                *[chunk_chain_key(d, cc) for d in documents],
            )
            exact = self._digest(docs_key, chunk_chain_key(query, cc))
            return exact, docs_key, query
        if path in _SCORE_PATHS:
            t1, t2 = body.get("text_1"), body.get("text_2")
            sides = []
            for side in (t1, t2):
                texts = [side] if isinstance(side, str) else side
                if not isinstance(texts, list) or not texts or not all(
                    isinstance(t, str) for t in texts
                ):
                    return None
                sides.append(texts)
            aux = json.dumps(
                {k: v for k, v in body.items()
                 if k not in ("text_1", "text_2")},
                sort_keys=True,
            )
            exact = self._digest(
                "score", str(model), aux,
                *[chunk_chain_key(t, cc) for ts in sides for t in ts],
                str(len(sides[0])),
            )
            return exact, None, None
        return None

    @staticmethod
    def _digest(*parts: str) -> str:
        h = hashlib.blake2b(digest_size=16)
        for p in parts:
            h.update(p.encode("utf-8"))
            h.update(b"\x00")
        return h.hexdigest()

    # -- exact tier ----------------------------------------------------------

    def lookup(self, exact_key: str) -> Optional[bytes]:
        """Stored response bytes for an exact-key hit, or None.  Expired
        entries are evicted on touch (TTL is a staleness bound, not a
        sweeper contract)."""
        entry = self._entries.get(exact_key)
        if entry is None:
            self.misses += 1
            return None
        body, stored_at, _docs_key, _qvec = entry
        if self._clock() - stored_at > self.ttl_s:
            self._evict(exact_key)
            self.misses += 1
            return None
        self._entries.move_to_end(exact_key)
        self.hits += 1
        return body

    def store(
        self,
        exact_key: str,
        response_bytes: bytes,
        docs_key: Optional[str] = None,
        query_vector: Optional[List[float]] = None,
    ) -> None:
        """Insert/refresh an entry, then evict LRU-first until the byte
        budget holds.  An answer larger than the whole budget is not
        cached (it would evict everything and still not fit)."""
        cost = len(response_bytes) + len(exact_key)
        if cost > self.max_bytes:
            return
        if exact_key in self._entries:
            self._evict(exact_key, count=False)
        self._entries[exact_key] = (
            response_bytes, self._clock(), docs_key, query_vector,
        )
        self._bytes += cost
        while self._bytes > self.max_bytes and self._entries:
            oldest = next(iter(self._entries))
            self._evict(oldest)

    def _evict(self, exact_key: str, count: bool = True) -> None:
        body, _ts, _dk, _qv = self._entries.pop(exact_key)
        self._bytes -= len(body) + len(exact_key)
        if count:
            self.evictions += 1

    # -- similarity tier (rerank) -------------------------------------------

    def similar_lookup(
        self, docs_key: str, query_vector: List[float]
    ) -> Optional[bytes]:
        """Best resident entry sharing ``docs_key`` whose stored query
        vector clears the cosine threshold.  Vectors are unit-norm
        (llama.encode L2-normalizes), so cosine is a dot product."""
        if self.similarity_threshold <= 0:
            return None
        best, best_sim = None, self.similarity_threshold
        now = self._clock()
        for key, (body, stored_at, dk, qvec) in self._entries.items():
            if dk != docs_key or qvec is None:
                continue
            if now - stored_at > self.ttl_s:
                continue
            sim = sum(a * b for a, b in zip(query_vector, qvec))
            if sim >= best_sim:
                best, best_sim = (key, body), sim
        if best is None:
            return None
        key, body = best
        self._entries.move_to_end(key)
        self.similar_hits += 1
        return body

    def has_docs_key(self, docs_key: str) -> bool:
        """Cheap pre-gate for the similarity tier: vectorizing the query
        costs one engine forward — only worth paying when some resident
        ranking could actually answer."""
        return any(dk == docs_key for _b, _t, dk, _qv in self._entries.values())

    # -- reads ---------------------------------------------------------------

    @property
    def size(self) -> int:
        return len(self._entries)

    @property
    def resident_bytes(self) -> int:
        return self._bytes


class EncodeCacheHooks:
    """proxy_hooks implementation fronting the encode lane.

    ``vectorize`` is an optional async callable ``text -> unit vector or
    None`` backed by the embed lane itself (app.py wires it to POST
    /v1/embeddings at an encode-capable backend); None keeps the
    similarity tier inert (exact tier only)."""

    def __init__(
        self,
        cache: EncodeCache,
        vectorize: Optional[Callable] = None,
    ):
        self.cache = cache
        self.vectorize = vectorize

    async def _read_json(self, request: web.Request) -> Optional[Dict[str, Any]]:
        # aiohttp caches the raw body; the data path's later read() is free.
        raw = await request.read()
        if not raw:
            return None
        try:
            body = json.loads(raw)
        except json.JSONDecodeError:
            return None
        return body if isinstance(body, dict) else None

    async def pre_route(
        self, request: web.Request, path: str
    ) -> Optional[web.StreamResponse]:
        from production_stack_tpu.router.experimental import (
            semantic_cache_hits,
            semantic_cache_misses,
            semantic_cache_size,
        )

        if path != _EMBED_PATH and path not in _RERANK_PATHS \
                and path not in _SCORE_PATHS:
            return None
        body = await self._read_json(request)
        if body is None:
            return None
        keys = self.cache.request_key(path, body)
        if keys is None:
            return None
        exact_key, docs_key, query_text = keys
        cached = self.cache.lookup(exact_key)
        semantic_cache_size.set(self.cache.size)
        if cached is not None:
            semantic_cache_hits.inc()
            return web.Response(
                body=cached,
                content_type="application/json",
                headers={"x-encode-cache": "hit"},
            )
        if (
            docs_key is not None
            and self.vectorize is not None
            and self.cache.similarity_threshold > 0
            and self.cache.has_docs_key(docs_key)
        ):
            qvec = await self.vectorize(query_text)
            if qvec is not None:
                near = self.cache.similar_lookup(docs_key, qvec)
                if near is not None:
                    semantic_cache_hits.inc()
                    return web.Response(
                        body=near,
                        content_type="application/json",
                        headers={"x-encode-cache": "similar"},
                    )
        semantic_cache_misses.inc()
        request[_REQ_KEY] = (exact_key, docs_key, query_text)
        return None

    def post_response_hook(self, request: web.Request, path: str):
        """Background store callable for a missed request, or None."""
        stash = request.get(_REQ_KEY)
        if stash is None:
            return None
        exact_key, docs_key, query_text = stash
        cache, vectorize = self.cache, self.vectorize

        async def store(body_json: Dict[str, Any], response_bytes: bytes) -> None:
            from production_stack_tpu.router.experimental import (
                semantic_cache_size,
            )

            try:
                payload = json.loads(response_bytes)
            except (ValueError, UnicodeDecodeError):
                return
            # Error envelopes are uncacheable (belt-and-braces on top of
            # the status==200 gate in process_request).
            if not isinstance(payload, dict) or "error" in payload:
                return
            qvec = None
            if (
                docs_key is not None
                and vectorize is not None
                and cache.similarity_threshold > 0
            ):
                # The stored query vector is what future near-duplicate
                # queries compare against; vectorized in the background
                # store, off the client's critical path.
                try:
                    qvec = await vectorize(query_text)
                except Exception:
                    logger.exception("encode-cache query vectorize failed")
            cache.store(
                exact_key, response_bytes,
                docs_key=docs_key, query_vector=qvec,
            )
            semantic_cache_size.set(cache.size)

        return store


class ChainedProxyHooks:
    """Compose proxy_hooks implementations: the first pre_route
    short-circuit wins; every post_response store callable runs (the
    app has ONE ``proxy_hooks`` slot — experimental PII/chat-cache hooks
    and the encode cache must coexist)."""

    def __init__(self, *hooks):
        self.hooks = [h for h in hooks if h is not None]

    async def pre_route(self, request, path):
        for h in self.hooks:
            resp = await h.pre_route(request, path)
            if resp is not None:
                return resp
        return None

    def post_response_hook(self, request, path):
        stores = [
            s for h in self.hooks
            for s in [h.post_response_hook(request, path)]
            if s is not None
        ]
        if not stores:
            return None
        if len(stores) == 1:
            return stores[0]

        async def fanout(body_json, response_bytes):
            for s in stores:
                await s(body_json, response_bytes)

        return fanout


def make_fleet_vectorizer(registry, chunk_chars: int = 1024):
    """An embed-lane-backed ``vectorize`` callable: POST /v1/embeddings
    for ONE text at an encode-capable backend through the router's own
    client session.  Any failure returns None — the similarity tier
    degrades to exact-only, never blocks the proxy path."""

    async def vectorize(text: str):
        from production_stack_tpu.router.routing.base import prefer_encode_pool
        from production_stack_tpu.router.service_discovery import (
            DISCOVERY_SERVICE,
        )
        from production_stack_tpu.router.services.request_service.request import (
            CLIENT_SESSION,
        )

        discovery = registry.get(DISCOVERY_SERVICE)
        session = registry.get(CLIENT_SESSION)
        if discovery is None or session is None:
            return None
        endpoints = prefer_encode_pool(
            [ep for ep in discovery.get_endpoint_info() if not ep.sleep]
        )
        if not endpoints:
            return None
        ep = endpoints[0]
        model = ep.model_names[0] if ep.model_names else None
        try:
            async with session.post(
                f"{ep.url}/v1/embeddings",
                json={"input": text, "model": model},
            ) as resp:
                if resp.status != 200:
                    return None
                payload = await resp.json()
            return payload["data"][0]["embedding"]
        except Exception:
            logger.debug("fleet vectorize failed", exc_info=True)
            return None

    return vectorize
