"""Two-phase disaggregated prefill/decode orchestration (the fleet half
of disagg serving; ROADMAP item 1, DistServe/Splitwise analogue).

``route_general_request`` calls :func:`run_prefill_phase` when the active
routing policy advertises ``two_phase`` (the ``disagg`` policy).  This
module owns phase 1 and the decision of what phase 2 looks like:

* pick a prefill-pool backend (least queued prompt tokens) and issue the
  prime call (``x-disagg-phase: prefill``) — the engine prefills, eagerly
  exports the prefix chain to the shared KV store, and answers with a
  handoff token instead of generating;
* re-check the request deadline between phases (a prime that ate the
  whole budget sheds a 504 here instead of occupying a decode slot);
* return the decode-phase candidate pool plus the compact handoff header
  the decode engine's admission-time prefetch keys on.

Every failure mode degrades to the **fused** single-backend path — the
pre-disagg behavior — and is counted under
``tpu_router:disagg_fallback_total{reason}``; a two-phase request never
500s because a role pool is missing, a breaker is open, or the store
dropped the chain (docs/robustness.md "Disagg handoff failure
semantics").

The handoff header is deliberately COMPACT: the full hash chain rides the
prime *response* (debuggability), but a 20k-token prompt is ~1,250 chain
keys — far past header budgets — and the decode engine recomputes the
identical chain from the same prompt anyway (content-keyed store).  The
header carries only the chain length, tail digest, prompt length, and the
model-identity key prefix for verification.
"""

from __future__ import annotations

import asyncio
import dataclasses
import json
import logging
import time
from typing import Any, Dict, List, Optional

import aiohttp
from aiohttp import web

from production_stack_tpu.router.service_discovery import (
    decode_capable,
    role_pool,
)

logger = logging.getLogger(__name__)

# Hard cap on the prime call: the per-request deadline (when present) is
# the real budget; this only bounds deadline-less requests against a
# wedged prefill backend (first XLA compile of a bucket legitimately
# takes minutes, so this errs long — the breaker covers dead backends).
PRIME_TIMEOUT_S = 300.0

# Handoff-token fields forwarded to the decode phase (see module
# docstring for why the full chain stays out of the header).
_HANDOFF_HEADER_FIELDS = ("chain_len", "chain_tail", "prompt_tokens", "px",
                          "exported", "block_size")


@dataclasses.dataclass
class DisaggOutcome:
    """What phase 2 should do.

    ``shed`` non-None: return it immediately (deadline expired between
    phases).  ``server_url`` non-None: phase 2 goes there (sticky fused
    fallback); otherwise the caller routes over ``endpoints``.
    """

    phase: str                      # "decode" (two-phase) | "fused"
    endpoints: List[Any]
    extra_headers: Dict[str, str] = dataclasses.field(default_factory=dict)
    server_url: Optional[str] = None
    shed: Optional[web.Response] = None
    fallback_reason: Optional[str] = None


def _fused(endpoints, reason: str, server_url: Optional[str] = None) -> DisaggOutcome:
    from production_stack_tpu.router.services import metrics_service as ms

    ms.disagg_fallback_total.labels(reason=reason).inc()
    ms.disagg_requests_total.labels(role="fused").inc()
    pool = decode_capable(endpoints) or endpoints
    return DisaggOutcome(
        phase="fused", endpoints=pool, server_url=server_url,
        fallback_reason=reason,
    )


async def prefill_phase(
    request: web.Request,
    registry,
    *,
    endpoints: List[Any],
    all_endpoints: List[Any],
    engine_stats: Dict[str, Any],
    request_stats: Dict[str, Any],
    body_bytes: bytes,
    forward_headers: Dict[str, str],
    request_id: str,
    deadline: Optional[float],
    endpoint_path: str,
    tracer=None,
) -> DisaggOutcome:
    """Phase 1 of the two-phase disagg data path.

    ``endpoints`` — model-filtered AND breaker-filtered; ``all_endpoints``
    — model-filtered only (distinguishes "no prefill pool configured"
    from "prefill pool exists but every breaker is open").
    """
    from production_stack_tpu.router.routing import ROUTING_SERVICE
    from production_stack_tpu.router.services import metrics_service as ms
    from production_stack_tpu.router.services.request_service.request import (
        CIRCUIT_BREAKER,
        CLIENT_SESSION,
    )

    prefill_pool = role_pool(endpoints, "prefill")
    decode_pool = decode_capable(endpoints)
    if not prefill_pool:
        reason = (
            "prefill_breaker_open"
            if role_pool(all_endpoints, "prefill")
            else "prefill_pool_empty"
        )
        return _fused(endpoints, reason)
    if not decode_pool:
        return _fused(endpoints, "decode_pool_empty")

    router = registry.require(ROUTING_SERVICE)
    prefill_url = router.select_prefill(
        prefill_pool, engine_stats, request_stats
    )
    breaker = registry.get(CIRCUIT_BREAKER)
    if breaker is not None and not breaker.on_attempt(prefill_url):
        # Half-open probe already in flight on the only viable pick.
        return _fused(endpoints, "prefill_breaker_open")

    session: aiohttp.ClientSession = registry.require(CLIENT_SESSION)
    prime_headers = dict(forward_headers)
    prime_headers["x-disagg-phase"] = "prefill"
    # The prime is an internal sub-request: derive its id so engine-side
    # traces join, but never collide with the decode phase's id.
    prime_headers["x-request-id"] = f"{request_id}-prefill"
    now = time.time()
    budget = PRIME_TIMEOUT_S
    if deadline is not None:
        # Floor of 250 ms: a deadline about to expire still gets a real
        # prime attempt — the between-phases re-check below (not an
        # artificially starved connect) decides whether to shed.
        budget = min(budget, max(0.25, deadline - now))
    t0 = time.time()
    handoff: Optional[Dict[str, Any]] = None
    try:
        async with session.post(
            f"{prefill_url}{endpoint_path}",
            data=body_bytes if body_bytes else None,
            headers=prime_headers,
            timeout=aiohttp.ClientTimeout(total=budget),
        ) as resp:
            if resp.status == 429:
                try:
                    retry_after = float(resp.headers.get("Retry-After", ""))
                except (TypeError, ValueError):
                    retry_after = None
                if breaker is not None:
                    breaker.on_backpressure(prefill_url, retry_after)
            elif resp.status >= 500:
                if breaker is not None:
                    breaker.on_failure(prefill_url)
            elif breaker is not None:
                breaker.on_success(prefill_url)
            if resp.status == 200:
                try:
                    body = await resp.json()
                    handoff = (body.get("disagg") or {}).get("handoff")
                except (ValueError, AttributeError, TypeError):
                    # 200 with a malformed/non-object body (a non-engine
                    # backend in the pool): degrade like any other prime
                    # failure — this path must never 500.
                    handoff = None
    except asyncio.CancelledError:
        raise
    except (aiohttp.ClientError, ConnectionResetError, asyncio.TimeoutError) as e:
        # Read-side idle timeouts are exempt from breaker counting on the
        # proxy path; the prime's bounded total timeout conflates the two,
        # so only count clear connect-stage/5xx failures — a None
        # t_connected-style split is not available through
        # ClientTimeout(total=...).  Conservative: connection errors
        # count, pure timeouts do not.
        if breaker is not None and not isinstance(e, asyncio.TimeoutError):
            breaker.on_failure(prefill_url)
        logger.warning("disagg prime against %s failed: %s", prefill_url, e)
    dt = time.time() - t0
    if tracer is not None:
        tracer.add_span(
            request_id, "router.disagg_prefill", t0, t0 + dt,
            server=prefill_url,
        )

    if handoff is None or not isinstance(handoff, dict):
        return _fused(endpoints, "prime_failed")

    ms.disagg_requests_total.labels(role="prefill").inc()
    ms.disagg_handoff_seconds.observe(dt)

    # Deadline re-check BETWEEN phases: the prime consumed real budget;
    # handing a dead-on-arrival generation to a decode backend would burn
    # a batch slot on an answer nobody is waiting for.
    if deadline is not None and time.time() >= deadline:
        ms.deadline_expired_total.inc()
        if tracer is not None:
            tracer.finish(
                request_id, error="deadline_expired", server=prefill_url
            )
        return DisaggOutcome(
            phase="shed", endpoints=endpoints,
            shed=web.json_response(
                {"error": {
                    "message": "request deadline expired between the "
                               "disagg prefill and decode phases",
                    "type": "deadline_expired", "code": 504,
                }},
                status=504,
            ),
        )

    if not handoff.get("exported"):
        # The prime ran but the chain never reached the shared store (no
        # remote KV configured, or export writer backlogged).  The
        # prefill backend holds the KV in its LOCAL prefix cache, so the
        # best degraded route is sticky: decode right there.
        return _fused(
            endpoints, "handoff_unexported", server_url=prefill_url
        )

    compact = {
        k: handoff[k] for k in _HANDOFF_HEADER_FIELDS if k in handoff
    }
    ms.disagg_requests_total.labels(role="decode").inc()
    return DisaggOutcome(
        phase="decode",
        endpoints=decode_pool,
        extra_headers={"x-disagg-handoff": json.dumps(compact)},
    )
