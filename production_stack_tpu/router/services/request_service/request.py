"""The router data path: parse -> rewrite -> route -> stream-proxy.

Reference counterpart: src/vllm_router/services/request_service/request.py
(route_general_request :120-196, process_request :44-117).  This is the
hottest path in the control plane; the proxy adds exactly one backend stream
and no buffering of the streamed body (SURVEY.md section 7, "Streaming proxy
fidelity").

Differences from the reference:

* pure-asyncio aiohttp instead of FastAPI+httpx (FastAPI is not a given on
  TPU images; one event loop, no thread hand-offs on the data path).
* stats hooks additionally record router-side queueing delay and per-chunk
  inter-token latency (reference monitors for these were never fed).
* failed/aborted requests are reported to the stats monitor instead of
  leaking in-flight counts.
"""

from __future__ import annotations

import asyncio
import json
import logging
import time
import uuid
from typing import Any, Dict, Optional

import aiohttp
from aiohttp import web

from production_stack_tpu.obs.trace import make_traceparent, parse_traceparent
from production_stack_tpu.router.capacity import (
    CAPACITY_MODEL,
    FLEET_ADMISSION,
    request_priority,
)
from production_stack_tpu.router.routing import ROUTING_SERVICE
from production_stack_tpu.router.service_discovery import DISCOVERY_SERVICE
from production_stack_tpu.utils.net import parse_deadline

logger = logging.getLogger(__name__)

# The read-side idle timeout (ClientSession sock_read tripping between
# response reads).  ONLY this timeout is exempt from circuit-breaker
# failure counting and connect-stage failover: the backend accepted the
# connection and is (possibly slowly) computing.  Connect-stage timeouts
# (aiohttp ConnectionTimeoutError, also a ServerTimeoutError subclass)
# must keep counting — a black-holed host that drops SYNs without an RST
# would otherwise never open its breaker.  getattr: SocketTimeoutError
# appeared in aiohttp 3.10; older versions collapse both into
# ServerTimeoutError, where we prefer the breaker-counting side.
_READ_IDLE_TIMEOUT_EXC = getattr(aiohttp, "SocketTimeoutError", ())

CLIENT_SESSION = "client_session"
REQUEST_STATS_MONITOR = "request_stats_monitor"
ENGINE_STATS_SCRAPER = "engine_stats_scraper"
REQUEST_REWRITER = "request_rewriter"
ROUTER_TRACER = "router_tracer"
# Per-backend circuit breaker (router/circuit_breaker.py); absent/None =
# breaker disabled, reproducing the pre-breaker proxy path exactly.
CIRCUIT_BREAKER = "circuit_breaker"
# Per-request connect-stage retry budget (int): at most 1 + budget
# backends are tried, so failover cannot amplify an overload across the
# whole fleet.  Absent = unbounded (legacy behavior, and what bare-registry
# unit tests get).
RETRY_BUDGET = "retry_budget"

# Encode-lane surface: requests to these paths run the engines' batched
# encode lane (embed/rerank/score), not the decode scan — they gate on
# the ENCODE pool's fleet headroom and route to encode-capable backends
# (docs/router.md "Encode lanes & semantic cache").
ENCODE_PATHS = ("/v1/embeddings", "/v1/rerank", "/rerank", "/v1/score", "/score")

# Headers that must not be forwarded either direction: hop-by-hop headers,
# plus encoding headers — aiohttp's client auto-decompresses the backend body
# and negotiates its own Accept-Encoding, so forwarding either would claim an
# encoding the relayed bytes no longer have.
_HOP_BY_HOP = {
    "host",
    "connection",
    "keep-alive",
    "proxy-authenticate",
    "proxy-authorization",
    "te",
    "trailers",
    "transfer-encoding",
    "upgrade",
    "content-length",
    "content-encoding",
    "accept-encoding",
    # Identity/trace headers the router owns and re-stamps explicitly on
    # both directions; forwarding the inbound casing too would emit the
    # header twice (dict keys are case-sensitive, the wire is not).
    "x-request-id",
    "traceparent",
    # Deadline header: normalized to absolute epoch seconds and re-stamped
    # explicitly (the inbound value may be the one we minted from a
    # `timeout` body field).
    "x-request-deadline",
    # Disagg control plane: the router mints these itself (the prime
    # marker and the handoff token) — an external client must not be able
    # to smuggle either through the proxy.
    "x-disagg-phase",
    "x-disagg-handoff",
}




def _forward_headers(headers) -> Dict[str, str]:
    return {k: v for k, v in headers.items() if k.lower() not in _HOP_BY_HOP}


def _error_response(status: int, message: str, type_: str = "invalid_request_error") -> web.Response:
    return web.json_response(
        {"error": {"message": message, "type": type_, "code": status}}, status=status
    )


async def route_general_request(
    request: web.Request, endpoint_path: str, background: Optional[Any] = None
) -> web.StreamResponse:
    """Proxy one OpenAI-style POST to the chosen serving engine.

    ``background`` is an optional async callable ``(body_json, response_text)``
    invoked after a successful non-streaming-aware completion (used by the
    semantic cache, reference request.py:113-117).
    """
    registry = request.app["registry"]
    in_router_time = time.time()
    # The request-id middleware (app.py) honors/mints x-request-id and
    # echoes it on every response; fall back here for direct callers.
    request_id = (
        request.get("request_id")
        or request.headers.get("x-request-id")
        or str(uuid.uuid4())
    )
    tracer = registry.get(ROUTER_TRACER)
    if tracer is not None and not tracer.enabled:
        tracer = None

    body_bytes = await request.read()
    try:
        body_json: Optional[Dict[str, Any]] = json.loads(body_bytes) if body_bytes else None
    except json.JSONDecodeError:
        return _error_response(400, "Request body is not valid JSON")

    requested_model = (body_json or {}).get("model")
    if body_json is not None and requested_model is None and endpoint_path.startswith("/v1/"):
        return _error_response(400, "Request body must include a 'model' field")

    # Rewrite hook (reference request.py:149-160).
    rewriter = registry.get(REQUEST_REWRITER)
    if rewriter is not None and body_json is not None:
        rewritten = rewriter.rewrite_request(body_json, requested_model, endpoint_path)
        if rewritten is not body_json:
            body_json = rewritten
            body_bytes = json.dumps(body_json).encode("utf-8")
        requested_model = (body_json or {}).get("model", requested_model)

    trace = None
    if tracer is not None:
        # Honor an inbound W3C traceparent (the caller's trace id) or mint
        # one; either way the id is forwarded to the engine so both
        # components' timelines join under it.  Started only AFTER the
        # body read + validation: a client dying mid-upload (or a rejected
        # body) must never leak a permanently-active trace.  The trace
        # start timestamp is still the receive time.
        trace = tracer.start(
            request_id,
            trace_id=parse_traceparent(request.headers.get("traceparent")),
            attrs={"path": endpoint_path},
            start=in_router_time,
        )

    def _reject(resp: web.Response, why: str) -> web.Response:
        """Close the trace on pre-proxy rejections so the ring shows them."""
        if tracer is not None:
            tracer.finish(request_id, error=why, status=resp.status)
        return resp

    # Deadline propagation: shed requests whose deadline already expired
    # in the router's own queue — forwarding them would waste an engine
    # batch slot on an answer nobody is waiting for.
    try:
        deadline = parse_deadline(request.headers, body_json, in_router_time)
    except ValueError as e:
        return _reject(_error_response(400, str(e)), "bad_deadline")
    if deadline is not None and time.time() >= deadline:
        from production_stack_tpu.router.services import metrics_service as ms

        ms.deadline_expired_total.inc()
        return _reject(
            _error_response(
                504, "request deadline expired in the router queue",
                "deadline_expired",
            ),
            "deadline_expired",
        )

    discovery = registry.require(DISCOVERY_SERVICE)
    endpoints = [ep for ep in discovery.get_endpoint_info() if not ep.sleep]
    scraper = registry.get(ENGINE_STATS_SCRAPER)
    # Avoid engines whose last /metrics scrape failed — as long as at least
    # one reachable engine remains (otherwise optimistically try them all;
    # the scrape may lag an engine's recovery).
    if scraper is not None:
        unreachable = scraper.get_unreachable_urls()
        if unreachable:
            reachable = [ep for ep in endpoints if ep.url not in unreachable]
            if reachable:
                endpoints = reachable
    if requested_model is not None:
        endpoints = [
            ep
            for ep in endpoints
            if not ep.model_names or requested_model in ep.model_names
        ]
    if not endpoints:
        return _reject(
            _error_response(
                400,
                f"Model '{requested_model}' not served by any healthy engine",
                "model_not_found",
            ),
            "model_not_found",
        )

    # Circuit breaker: opened backends receive no traffic (a half-open
    # probe-ready backend passes the filter; the probe slot is consumed in
    # process_request when routing actually picks it).  Backpressured
    # engines (recent 429) lose routing weight while alternatives exist.
    breaker = registry.get(CIRCUIT_BREAKER)
    if breaker is not None:
        from production_stack_tpu.router.routing.base import (
            deprioritize_backpressured,
            filter_circuit_available,
        )

        available = filter_circuit_available(endpoints, breaker)
        if not available:
            return _reject(
                _error_response(
                    503,
                    f"All serving engines for model '{requested_model}' "
                    "have open circuit breakers",
                    "circuit_open",
                ),
                "circuit_open",
            )
        endpoints = deprioritize_backpressured(available, breaker)

    engine_stats = scraper.get_engine_stats() if scraper else {}
    monitor = registry.get(REQUEST_STATS_MONITOR)
    request_stats = monitor.get_request_stats(time.time()) if monitor else {}

    # Encode lane: embed/rerank/score requests prefer the dedicated
    # encode pool (role-less fused backends serve both; prefill/decode
    # members are reserved for generation) and gate on the ENCODE
    # pool's headroom below — an embed burst sheds against its own
    # knee instead of stretching generation ITL.
    lane = "encode" if endpoint_path in ENCODE_PATHS else "generate"
    if lane == "encode":
        from production_stack_tpu.router.routing.base import prefer_encode_pool

        endpoints = prefer_encode_pool(endpoints)

    # Fleet-level admission (router/capacity.py): when the online
    # capacity model estimates the admission pool's headroom exhausted,
    # shed HERE with a structured 429 + Retry-After — before a routing
    # decision, a backend connect, or an engine queue slot is spent.
    # Fleet sheds therefore strictly precede engine 429s in an overload
    # (docs/robustness.md "Fleet admission & autoscaling contract").
    admission = registry.get(FLEET_ADMISSION)
    if admission is not None:
        shed = admission.check(
            endpoints, engine_stats, request_stats,
            priority=request_priority(request.headers, body_json),
            monitor=monitor,
            lane=lane,
        )
        if shed is not None:
            from production_stack_tpu.router.services import (
                metrics_service as ms,
            )

            ms.fleet_admission_rejected_total.labels(reason=shed.reason).inc()
            resp = web.json_response(
                {
                    "error": {
                        "message": (
                            "fleet overloaded: estimated "
                            f"{shed.pool}-pool headroom exhausted "
                            f"({shed.headroom:.1f}/{shed.capacity:.1f} "
                            "slots free)"
                        ),
                        "type": "fleet_overloaded",
                        "code": 429,
                        "detail": {
                            "reason": shed.reason,
                            "pool": shed.pool,
                            "headroom_slots": round(shed.headroom, 2),
                            "capacity_slots": round(shed.capacity, 2),
                        },
                    }
                },
                status=429,
                headers={"Retry-After": str(max(1, int(shed.retry_after_s)))},
            )
            return _reject(resp, f"fleet_shed_{shed.reason}")

    router = registry.require(ROUTING_SERVICE)

    # Two-phase disaggregated prefill/decode (routing policy `disagg`):
    # prime a prefill-pool backend (which eagerly exports the prefix
    # chain), then route the generation to a decode-pool backend whose
    # admission-time prefetch imports it.  Every failure mode degrades to
    # the fused single-backend path below — never a 500
    # (docs/robustness.md "Disagg handoff failure semantics").
    server_url: Optional[str] = None
    extra_headers: Optional[Dict[str, str]] = None
    if (
        getattr(router, "two_phase", False)
        and body_json is not None
        and endpoint_path in ("/v1/chat/completions", "/v1/completions")
    ):
        from production_stack_tpu.router.services.request_service.disagg import (
            prefill_phase,
        )

        prime_fwd = _forward_headers(request.headers)
        if deadline is not None:
            prime_fwd["x-request-deadline"] = repr(float(deadline))
        if trace is not None:
            prime_fwd["traceparent"] = make_traceparent(trace.trace_id)
        outcome = await prefill_phase(
            request, registry,
            endpoints=endpoints,
            all_endpoints=[ep for ep in discovery.get_endpoint_info()
                           if not ep.sleep],
            engine_stats=engine_stats,
            request_stats=request_stats,
            body_bytes=body_bytes,
            forward_headers=prime_fwd,
            request_id=request_id,
            deadline=deadline,
            endpoint_path=endpoint_path,
            tracer=tracer,
        )
        if outcome.shed is not None:
            return outcome.shed
        endpoints = outcome.endpoints
        extra_headers = outcome.extra_headers or None
        server_url = outcome.server_url

    if server_url is None:
        try:
            server_url = router.route_request(
                endpoints, engine_stats, request_stats, request, body_json
            )
        except ValueError as e:
            return _reject(
                _error_response(503, str(e), "service_unavailable"),
                "routing_failed",
            )

    if tracer is not None and trace is not None:
        tracer.add_span(
            request_id, "router.route", in_router_time, time.time(),
            server=server_url,
        )
        tracer.set_attrs(request_id, model=requested_model, server=server_url)

    logger.debug(
        "Routing request %s (model=%s) to %s at %.6f, took %.3f ms",
        request_id,
        requested_model,
        server_url,
        in_router_time,
        (time.time() - in_router_time) * 1e3,
    )

    # Connect-stage failover list: if the routed backend dies between
    # scrapes, surviving replicas still serve the request (the reference
    # 502s here — SURVEY.md section 5; see test_router_e2e).  Once a byte
    # has streamed there is no failover (the client has partial state).
    fallback_urls = [ep.url for ep in endpoints if ep.url != server_url]

    return await process_request(
        request,
        body_bytes=body_bytes,
        body_json=body_json,
        server_url=server_url,
        endpoint_path=endpoint_path,
        request_id=request_id,
        in_router_time=in_router_time,
        background=background,
        fallback_urls=fallback_urls,
        deadline=deadline,
        extra_headers=extra_headers,
    )


async def process_request(
    request: web.Request,
    *,
    body_bytes: bytes,
    body_json: Optional[Dict[str, Any]],
    server_url: str,
    endpoint_path: str,
    request_id: str,
    in_router_time: float,
    background: Optional[Any] = None,
    fallback_urls: Optional[list] = None,
    deadline: Optional[float] = None,
    extra_headers: Optional[Dict[str, str]] = None,
) -> web.StreamResponse:
    """Open one backend stream and relay chunks, feeding the stats lifecycle
    (reference process_request, request.py:44-117).

    ``fallback_urls``: tried in order when the routed backend fails at the
    connect stage (before any response byte), capped by the per-request
    retry budget so failover cannot amplify an overload.  Mid-stream
    failures never fail over — the client already holds partial state."""
    registry = request.app["registry"]
    monitor = registry.get(REQUEST_STATS_MONITOR)
    session: aiohttp.ClientSession = registry.require(CLIENT_SESSION)
    breaker = registry.get(CIRCUIT_BREAKER)
    retry_budget = registry.get(RETRY_BUDGET)
    tracer = registry.get(ROUTER_TRACER)
    if tracer is not None and not tracer.enabled:
        tracer = None
    trace = tracer.get(request_id) if tracer is not None else None

    headers = _forward_headers(request.headers)
    headers["x-request-id"] = request_id
    if extra_headers:
        # Router-minted control headers (the disagg handoff token) —
        # added after the hop-by-hop strip so clients cannot spoof them.
        headers.update(extra_headers)
    if deadline is not None:
        # Normalized absolute form, whatever the client sent (header or
        # `timeout` body field) — the engine enforces it at admission and
        # in its scheduler-pass sweep.
        headers["x-request-deadline"] = repr(float(deadline))
    if trace is not None:
        # Propagate the trace context so the engine's timeline joins this
        # one under the same trace id (/debug/requests/{id}).
        headers["traceparent"] = make_traceparent(trace.trace_id)
    elif request.headers.get("traceparent"):
        # Tracing off: stay a transparent proxy for the caller's context
        # (it was stripped from the generic forward set above).
        headers["traceparent"] = request.headers["traceparent"]

    candidates = [server_url] + list(fallback_urls or [])
    if retry_budget is not None:
        # Retry budget: the routed backend + at most `retry_budget`
        # failover attempts.  Under a fleet-wide brownout, unbounded
        # failover would replay every request against every backend —
        # multiplying the very load that caused the failures.
        candidates = candidates[: 1 + max(0, int(retry_budget))]
    collected: list = []
    want_store = background is not None
    # First connect attempt's start: router.queue must end HERE, not at
    # the successful attempt's connect start — otherwise a dead backend's
    # connect timeout would masquerade as router queueing.
    first_connect0: Optional[float] = None

    for attempt, url in enumerate(candidates):
        if deadline is not None and attempt > 0 and time.time() >= deadline:
            # Failover burned the remaining budget: shed instead of
            # handing a dead-on-arrival request to the next backend.
            from production_stack_tpu.router.services import (
                metrics_service as ms,
            )

            ms.deadline_expired_total.inc()
            if tracer is not None:
                tracer.finish(request_id, error="deadline_expired", server=url)
            return _error_response(
                504, "request deadline expired during connect-stage failover",
                "deadline_expired",
            )
        if breaker is not None and not breaker.on_attempt(url):
            # Open circuit (or a half-open probe already in flight):
            # skip without counting a failure.
            continue
        if monitor:
            monitor.on_new_request(url, request_id, in_router_time)
        first_chunk_seen = False
        t_first: Optional[float] = None
        t_connected: Optional[float] = None
        response: Optional[web.StreamResponse] = None
        t_connect0 = time.time()
        if first_connect0 is None:
            first_connect0 = t_connect0

        def _fail_spans() -> None:
            """Attach whatever phases completed before a failure — the
            slow/failed requests are exactly the ones the debug surface
            must explain, so their timelines can't be span-less."""
            if tracer is None:
                return
            tracer.add_span(
                request_id, "router.queue", in_router_time, first_connect0
            )
            if t_connected is not None:
                tracer.add_span(
                    request_id, "router.backend_connect", t_connect0,
                    t_connected, server=url,
                )
                if t_first is not None:
                    tracer.add_span(
                        request_id, "router.first_token", t_connected, t_first
                    )

        try:
            async with session.request(
                request.method,
                f"{url}{endpoint_path}",
                data=body_bytes if body_bytes else None,
                headers=headers,
            ) as backend:
                t_connected = time.time()
                if breaker is not None:
                    if backend.status == 429:
                        # Engine shedding: backpressure, never a breaker
                        # failure (routing weight drops instead).
                        try:
                            retry_after = float(
                                backend.headers.get("Retry-After", "")
                            )
                        except (TypeError, ValueError):
                            retry_after = None
                        breaker.on_backpressure(url, retry_after)
                        # The same event is a ZERO-HEADROOM observation
                        # for the fleet capacity model: the engine told
                        # us its bound, so fleet admission stops sending
                        # work its way for the advertised window.
                        capacity = registry.get(CAPACITY_MODEL)
                        if capacity is not None:
                            capacity.on_backpressure(url, retry_after)
                    elif backend.status >= 500:
                        breaker.on_failure(url)
                    else:
                        breaker.on_success(url)
                if monitor:
                    monitor.on_backend_connected(url, request_id, t_connected)
                if extra_headers and "x-disagg-handoff" in extra_headers:
                    # Decode-phase prefetch outcome: anything but a full
                    # chain import means the decode engine recomputed the
                    # prefill locally — the in-place fused fallback the
                    # two-phase contract degrades to (never a third
                    # backend, never a failure).
                    px_outcome = backend.headers.get("x-disagg-prefix")
                    if px_outcome is not None and px_outcome != "hit":
                        from production_stack_tpu.router.services import (
                            metrics_service as ms,
                        )

                        ms.disagg_fallback_total.labels(
                            reason="prefix_miss"
                        ).inc()
                resp_headers = _forward_headers(backend.headers)
                # Echo the request id on the proxied response too (the
                # engine may predate the header; the client must always
                # get it back, streaming included).
                resp_headers["x-request-id"] = request_id
                response = web.StreamResponse(
                    status=backend.status, headers=resp_headers
                )
                await response.prepare(request)
                async for chunk in backend.content.iter_any():
                    if not chunk:
                        continue
                    now = time.time()
                    if not first_chunk_seen:
                        t_first = now
                        first_chunk_seen = True
                        if monitor:
                            # Seeds the token clock + counts this chunk; no
                            # ITL sample (first chunk defines no interval).
                            # The engine stamps '"compile": true' into the
                            # first chunk (SSE or JSON body alike) when an
                            # XLA compile fired inside the request: a byte
                            # sniff — not a parse — keeps that cold-start
                            # sample out of the compile-excluded TTFT
                            # window on the proxy hot path.
                            tainted = (
                                b'"compile": true' in chunk
                                or b'"compile":true' in chunk
                            )
                            monitor.on_request_response(
                                url, request_id, now,
                                compile_tainted=tainted,
                            )
                    elif monitor:
                        monitor.on_token_chunk(url, request_id, now)
                    if want_store:
                        collected.append(chunk)
                    await response.write(chunk)
                await response.write_eof()
            t_end = time.time()
            if monitor:
                monitor.on_request_complete(url, request_id, t_end)
            if tracer is not None:
                # Routing decision -> backend connect -> first token ->
                # stream end (the span set the ISSUE names; router.queue +
                # router.backend_connect are the non-overlapping phases
                # the /debug join scores against engine spans).
                tracer.add_span(
                    request_id, "router.queue", in_router_time, first_connect0
                )
                if attempt > 0:
                    # Time burned on dead backends before this one; keeps
                    # the timeline honest without blaming router.queue.
                    tracer.add_span(
                        request_id, "router.failover", first_connect0,
                        t_connect0, attempts=attempt,
                    )
                tracer.add_span(
                    request_id, "router.backend_connect", t_connect0,
                    t_connected, server=url,
                )
                if t_first is not None:
                    tracer.add_span(
                        request_id, "router.first_token", t_connected, t_first
                    )
                    tracer.add_span(
                        request_id, "router.stream", t_first, t_end
                    )
                tracer.finish(
                    request_id, end=t_end, server=url,
                    status=response.status,
                )
        except asyncio.CancelledError:
            # Client disconnected (or server shutdown): release in-flight
            # stats, then propagate — cancellation must not be swallowed.
            if monitor:
                monitor.on_request_failed(url, request_id, time.time())
            if tracer is not None:
                _fail_spans()
                tracer.finish(request_id, error="client_disconnect", server=url)
            raise
        except (aiohttp.ClientError, ConnectionResetError) as e:
            if monitor:
                monitor.on_request_failed(url, request_id, time.time())
            idle_timeout = isinstance(e, _READ_IDLE_TIMEOUT_EXC)
            if breaker is not None and not idle_timeout:
                # sock_read idle timeouts are deliberately NOT breaker
                # failures: the backend accepted the connection — it may
                # just be slow (first XLA compile of a bucket can take
                # minutes with zero response bytes).  The per-stream
                # teardown is the remedy; opening the circuit would cut
                # ALL traffic to a healthy-but-compiling backend.
                # Connect-stage timeouts DO count (see _READ_IDLE_TIMEOUT_EXC).
                breaker.on_failure(url)
            if response is not None:
                # Mid-stream failure: the client already has a partial
                # body; terminate the stream (reference behavior, SURVEY.md
                # section 5 "no request retry/failover mid-stream").
                logger.warning("Backend %s failed mid-stream: %s", url, e)
                if tracer is not None:
                    _fail_spans()
                    tracer.finish(
                        request_id, error="mid_stream_failure", server=url
                    )
                raise
            if idle_timeout:
                # The backend accepted the request and is mid-compute
                # (headers not sent yet: a long non-streaming generation
                # past --stream-idle-timeout-s).  Failing over would
                # re-execute the WHOLE completion on another engine while
                # the first keeps decoding until the disconnect-abort
                # lands — duplicated generation load, not recovery.  Shed
                # to the client instead.
                logger.warning(
                    "Backend %s idle-read timeout before response headers "
                    "(%s); shedding instead of replaying", url, e,
                )
                if tracer is not None:
                    _fail_spans()
                    tracer.finish(request_id, error="backend_timeout", server=url)
                return _error_response(
                    504,
                    "Serving engine produced no response bytes within the "
                    "idle-read timeout",
                    "backend_timeout",
                )
            if attempt + 1 < len(candidates):
                logger.warning(
                    "Backend %s unreachable (%s); failing over to %s",
                    url, e, candidates[attempt + 1],
                )
                continue
            logger.warning("Backend %s failed before response: %s", url, e)
            if tracer is not None:
                _fail_spans()
                tracer.finish(request_id, error="bad_gateway", server=url)
            return _error_response(
                502, "All serving engines for this model are unreachable",
                "bad_gateway",
            )

        # Only feed the store hook successful responses: backend error
        # bodies (429/503, or vLLM's {"object": "error"} shape) must never
        # be cached and replayed as hits.
        if (
            want_store
            and collected
            and body_json is not None
            and response is not None
            and response.status == 200
        ):
            try:
                await background(body_json, b"".join(collected))
            except Exception:
                logger.exception("post-response background hook failed")
        return response

    # Every candidate was skipped without an attempt (circuit open on all
    # of them, or the failover list ran dry on breaker skips alone).
    if tracer is not None:
        tracer.finish(request_id, error="circuit_open")
    return _error_response(
        503, "All serving engines for this model have open circuit breakers",
        "circuit_open",
    )
