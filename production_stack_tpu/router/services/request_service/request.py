"""The router data path: parse -> rewrite -> route -> stream-proxy.

Reference counterpart: src/vllm_router/services/request_service/request.py
(route_general_request :120-196, process_request :44-117).  This is the
hottest path in the control plane; the proxy adds exactly one backend stream
and no buffering of the streamed body (SURVEY.md section 7, "Streaming proxy
fidelity").

Differences from the reference:

* pure-asyncio aiohttp instead of FastAPI+httpx (FastAPI is not a given on
  TPU images; one event loop, no thread hand-offs on the data path).
* stats hooks additionally record router-side queueing delay and per-chunk
  inter-token latency (reference monitors for these were never fed).
* failed/aborted requests are reported to the stats monitor instead of
  leaking in-flight counts.
"""

from __future__ import annotations

import asyncio
import json
import logging
import time
import uuid
from typing import Any, Dict, Optional

import aiohttp
from aiohttp import web

from production_stack_tpu.router.routing import ROUTING_SERVICE
from production_stack_tpu.router.service_discovery import DISCOVERY_SERVICE

logger = logging.getLogger(__name__)

CLIENT_SESSION = "client_session"
REQUEST_STATS_MONITOR = "request_stats_monitor"
ENGINE_STATS_SCRAPER = "engine_stats_scraper"
REQUEST_REWRITER = "request_rewriter"

# Headers that must not be forwarded either direction: hop-by-hop headers,
# plus encoding headers — aiohttp's client auto-decompresses the backend body
# and negotiates its own Accept-Encoding, so forwarding either would claim an
# encoding the relayed bytes no longer have.
_HOP_BY_HOP = {
    "host",
    "connection",
    "keep-alive",
    "proxy-authenticate",
    "proxy-authorization",
    "te",
    "trailers",
    "transfer-encoding",
    "upgrade",
    "content-length",
    "content-encoding",
    "accept-encoding",
}


def _forward_headers(headers) -> Dict[str, str]:
    return {k: v for k, v in headers.items() if k.lower() not in _HOP_BY_HOP}


def _error_response(status: int, message: str, type_: str = "invalid_request_error") -> web.Response:
    return web.json_response(
        {"error": {"message": message, "type": type_, "code": status}}, status=status
    )


async def route_general_request(
    request: web.Request, endpoint_path: str, background: Optional[Any] = None
) -> web.StreamResponse:
    """Proxy one OpenAI-style POST to the chosen serving engine.

    ``background`` is an optional async callable ``(body_json, response_text)``
    invoked after a successful non-streaming-aware completion (used by the
    semantic cache, reference request.py:113-117).
    """
    registry = request.app["registry"]
    in_router_time = time.time()
    request_id = request.headers.get("x-request-id") or str(uuid.uuid4())

    body_bytes = await request.read()
    try:
        body_json: Optional[Dict[str, Any]] = json.loads(body_bytes) if body_bytes else None
    except json.JSONDecodeError:
        return _error_response(400, "Request body is not valid JSON")

    requested_model = (body_json or {}).get("model")
    if body_json is not None and requested_model is None and endpoint_path.startswith("/v1/"):
        return _error_response(400, "Request body must include a 'model' field")

    # Rewrite hook (reference request.py:149-160).
    rewriter = registry.get(REQUEST_REWRITER)
    if rewriter is not None and body_json is not None:
        rewritten = rewriter.rewrite_request(body_json, requested_model, endpoint_path)
        if rewritten is not body_json:
            body_json = rewritten
            body_bytes = json.dumps(body_json).encode("utf-8")
        requested_model = (body_json or {}).get("model", requested_model)

    discovery = registry.require(DISCOVERY_SERVICE)
    endpoints = [ep for ep in discovery.get_endpoint_info() if not ep.sleep]
    scraper = registry.get(ENGINE_STATS_SCRAPER)
    # Avoid engines whose last /metrics scrape failed — as long as at least
    # one reachable engine remains (otherwise optimistically try them all;
    # the scrape may lag an engine's recovery).
    if scraper is not None:
        unreachable = scraper.get_unreachable_urls()
        if unreachable:
            reachable = [ep for ep in endpoints if ep.url not in unreachable]
            if reachable:
                endpoints = reachable
    if requested_model is not None:
        endpoints = [
            ep
            for ep in endpoints
            if not ep.model_names or requested_model in ep.model_names
        ]
    if not endpoints:
        return _error_response(
            400, f"Model '{requested_model}' not served by any healthy engine", "model_not_found"
        )

    engine_stats = scraper.get_engine_stats() if scraper else {}
    monitor = registry.get(REQUEST_STATS_MONITOR)
    request_stats = monitor.get_request_stats(time.time()) if monitor else {}

    router = registry.require(ROUTING_SERVICE)
    try:
        server_url = router.route_request(
            endpoints, engine_stats, request_stats, request, body_json
        )
    except ValueError as e:
        return _error_response(503, str(e), "service_unavailable")

    logger.debug(
        "Routing request %s (model=%s) to %s at %.6f, took %.3f ms",
        request_id,
        requested_model,
        server_url,
        in_router_time,
        (time.time() - in_router_time) * 1e3,
    )

    # Connect-stage failover list: if the routed backend dies between
    # scrapes, surviving replicas still serve the request (the reference
    # 502s here — SURVEY.md section 5; see test_router_e2e).  Once a byte
    # has streamed there is no failover (the client has partial state).
    fallback_urls = [ep.url for ep in endpoints if ep.url != server_url]

    return await process_request(
        request,
        body_bytes=body_bytes,
        body_json=body_json,
        server_url=server_url,
        endpoint_path=endpoint_path,
        request_id=request_id,
        in_router_time=in_router_time,
        background=background,
        fallback_urls=fallback_urls,
    )


async def process_request(
    request: web.Request,
    *,
    body_bytes: bytes,
    body_json: Optional[Dict[str, Any]],
    server_url: str,
    endpoint_path: str,
    request_id: str,
    in_router_time: float,
    background: Optional[Any] = None,
    fallback_urls: Optional[list] = None,
) -> web.StreamResponse:
    """Open one backend stream and relay chunks, feeding the stats lifecycle
    (reference process_request, request.py:44-117).

    ``fallback_urls``: tried in order when the routed backend fails at the
    connect stage (before any response byte).  Mid-stream failures never
    fail over — the client already holds partial state."""
    registry = request.app["registry"]
    monitor = registry.get(REQUEST_STATS_MONITOR)
    session: aiohttp.ClientSession = registry.require(CLIENT_SESSION)

    headers = _forward_headers(request.headers)
    headers["x-request-id"] = request_id

    candidates = [server_url] + list(fallback_urls or [])
    collected: list = []
    want_store = background is not None

    for attempt, url in enumerate(candidates):
        if monitor:
            monitor.on_new_request(url, request_id, in_router_time)
        first_chunk_seen = False
        response: Optional[web.StreamResponse] = None
        try:
            async with session.request(
                request.method,
                f"{url}{endpoint_path}",
                data=body_bytes if body_bytes else None,
                headers=headers,
            ) as backend:
                if monitor:
                    monitor.on_backend_connected(url, request_id, time.time())
                response = web.StreamResponse(
                    status=backend.status, headers=_forward_headers(backend.headers)
                )
                await response.prepare(request)
                async for chunk in backend.content.iter_any():
                    if not chunk:
                        continue
                    now = time.time()
                    if monitor:
                        if not first_chunk_seen:
                            # Seeds the token clock + counts this chunk; no
                            # ITL sample (first chunk defines no interval).
                            monitor.on_request_response(url, request_id, now)
                            first_chunk_seen = True
                        else:
                            monitor.on_token_chunk(url, request_id, now)
                    if want_store:
                        collected.append(chunk)
                    await response.write(chunk)
                await response.write_eof()
            if monitor:
                monitor.on_request_complete(url, request_id, time.time())
        except asyncio.CancelledError:
            # Client disconnected (or server shutdown): release in-flight
            # stats, then propagate — cancellation must not be swallowed.
            if monitor:
                monitor.on_request_failed(url, request_id, time.time())
            raise
        except (aiohttp.ClientError, ConnectionResetError) as e:
            if monitor:
                monitor.on_request_failed(url, request_id, time.time())
            if response is not None:
                # Mid-stream failure: the client already has a partial
                # body; terminate the stream (reference behavior, SURVEY.md
                # section 5 "no request retry/failover mid-stream").
                logger.warning("Backend %s failed mid-stream: %s", url, e)
                raise
            if attempt + 1 < len(candidates):
                logger.warning(
                    "Backend %s unreachable (%s); failing over to %s",
                    url, e, candidates[attempt + 1],
                )
                continue
            logger.warning("Backend %s failed before response: %s", url, e)
            return _error_response(
                502, "All serving engines for this model are unreachable",
                "bad_gateway",
            )

        # Only feed the store hook successful responses: backend error
        # bodies (429/503, or vLLM's {"object": "error"} shape) must never
        # be cached and replayed as hits.
        if (
            want_store
            and collected
            and body_json is not None
            and response is not None
            and response.status == 200
        ):
            try:
                await background(body_json, b"".join(collected))
            except Exception:
                logger.exception("post-response background hook failed")
        return response
