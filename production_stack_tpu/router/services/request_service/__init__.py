"""Request proxy service (reference: src/vllm_router/services/request_service/)."""

from production_stack_tpu.router.services.request_service.request import (
    route_general_request,
)
from production_stack_tpu.router.services.request_service.rewriter import (
    NoopRequestRewriter,
    RequestRewriter,
    get_request_rewriter,
)

__all__ = [
    "route_general_request",
    "RequestRewriter",
    "NoopRequestRewriter",
    "get_request_rewriter",
]
