"""Request rewriting hook (reference: services/request_service/rewriter.py:17-107).

Rewriters mutate the request body before routing/proxying (prompt
engineering, model-name canonicalization, default-parameter injection).
"""

from __future__ import annotations

from typing import Any, Dict, Optional


class RequestRewriter:
    def rewrite_request(
        self, body: Dict[str, Any], model: str, endpoint_path: str
    ) -> Dict[str, Any]:
        raise NotImplementedError


class NoopRequestRewriter(RequestRewriter):
    def rewrite_request(
        self, body: Dict[str, Any], model: str, endpoint_path: str
    ) -> Dict[str, Any]:
        return body


class ModelAliasRewriter(RequestRewriter):
    """Maps public model aliases to backend model names (e.g. expose
    ``gpt-4`` while the engines serve ``llama-3-8b``).  The reference parses
    static aliases but has no rewriter wired to apply them."""

    def __init__(self, aliases: Dict[str, str]):
        self.aliases = dict(aliases)

    def rewrite_request(
        self, body: Dict[str, Any], model: str, endpoint_path: str
    ) -> Dict[str, Any]:
        if model in self.aliases:
            body = dict(body)
            body["model"] = self.aliases[model]
        return body


_REWRITERS = {
    "noop": NoopRequestRewriter,
}


def get_request_rewriter(
    name: str = "noop", aliases: Optional[Dict[str, str]] = None
) -> RequestRewriter:
    """Factory (reference rewriter.py:97-107); aliases take priority."""
    if aliases:
        return ModelAliasRewriter(aliases)
    try:
        return _REWRITERS[name]()
    except KeyError:
        raise ValueError(f"Unknown request rewriter {name!r}") from None
