"""Router-level Prometheus gauges, labeled by backend server.

Reference counterpart: src/vllm_router/services/metrics_service/__init__.py:1-31.
Extended with the gauges the reference's dashboard charts but never exports
(router queueing delay) and TPU engine mirrors (HBM KV usage, prefix hit
rate) so one scrape of the router suffices for the whole stack.
"""

from prometheus_client import Counter, Gauge, Histogram

current_qps = Gauge("tpu_router:current_qps", "Sliding-window QPS", ["server"])
avg_ttft = Gauge("tpu_router:avg_ttft", "Average time-to-first-token (s)", ["server"])
avg_latency = Gauge(
    "tpu_router:avg_latency", "Average end-to-end request latency (s)", ["server"]
)
avg_itl = Gauge("tpu_router:avg_itl", "Average inter-token latency (s)", ["server"])
avg_decoding_length = Gauge(
    "tpu_router:avg_decoding_length", "Average streamed chunks per request", ["server"]
)
queueing_delay = Gauge(
    "tpu_router:queueing_delay_seconds",
    "Router-side queueing delay: receive -> backend connect (s)",
    ["server"],
)
num_prefill_requests = Gauge(
    "tpu_router:num_prefill_requests", "Requests awaiting first token", ["server"]
)
num_decoding_requests = Gauge(
    "tpu_router:num_decoding_requests", "Requests streaming tokens", ["server"]
)
num_requests_finished = Gauge(
    "tpu_router:num_requests_finished", "Completed requests", ["server"]
)
num_requests_uncompleted = Gauge(
    "tpu_router:num_requests_uncompleted", "In-flight requests", ["server"]
)
healthy_pods_total = Gauge(
    "tpu_router:healthy_pods_total", "Healthy serving-engine endpoints", ["model"]
)
# Engine-side mirrors (scraped via EngineStatsScraper).
engine_kv_usage_perc = Gauge(
    "tpu_router:engine_hbm_kv_usage_perc", "Engine TPU HBM KV pool usage (0-1)", ["server"]
)
engine_prefix_cache_hit_rate = Gauge(
    "tpu_router:engine_prefix_cache_hit_rate", "Engine prefix-cache hit rate (0-1)", ["server"]
)
engine_queue_depth = Gauge(
    "tpu_router:engine_num_requests_waiting", "Engine waiting-queue depth", ["server"]
)
# Overload protection (docs/robustness.md).
circuit_state = Gauge(
    "tpu_router:circuit_state",
    "Per-backend circuit breaker state (0=closed, 1=half_open, 2=open)",
    ["server"],
)
# Compile-excluded windowed TTFT p95: samples whose first chunk the engine
# stamped '"compile": true' (an XLA compile fired inside the request) are
# left out; the gap to raw TTFT p95 is the cold-start compile cost.
ttft_clean_p95 = Gauge(
    "tpu_router:ttft_clean_p95_seconds",
    "Windowed TTFT p95 excluding compile-tainted samples (s)",
    ["server"],
)
# Router-side trace-ring evictions (byte/count bound) — mirrors the
# engine's tpu:obs_trace_dropped_total on the router's own tracer.
obs_trace_dropped_total = Counter(
    "tpu_router:obs_trace_dropped",
    "Router request-trace ring evictions (byte/count bound)",
)
deadline_expired_total = Counter(
    "tpu_router:deadline_expired_total",
    "Requests shed by the router because their deadline expired before "
    "(or during) backend connect",
)

# -- fleet-level admission control (router/capacity.py) --------------------
# The router is the fleet's overload firewall: when the online capacity
# model estimates fleet headroom exhausted, requests shed HERE with a
# structured 429 + Retry-After — before any engine queue grows.  Closed
# reason set, pre-seeded so dashboards and rate() see stable label sets
# from boot: "no_headroom" (the admission pool's spare slots hit zero),
# "low_priority" (degradable work shed early while headroom is merely low).
fleet_admission_rejected_total = Counter(
    "tpu_router:fleet_admission_rejected_total",
    "Requests shed at the router by fleet-level admission control, by reason",
    ["reason"],
)
for _shed_reason in ("no_headroom", "low_priority"):
    fleet_admission_rejected_total.labels(reason=_shed_reason)
# Estimated spare request slots per admission pool ("fleet" for fused
# fleets; "prefill"/"decode" under disagg role pools) — the autoscaling
# surface's scale-up signal (observability/prom-adapter.yaml).
fleet_headroom_slots = Gauge(
    "tpu_router:fleet_headroom_slots",
    "Capacity-model fleet headroom in spare request slots, per pool",
    ["pool"],
)
# Per-backend learned capacity: max useful concurrency and the free
# fraction (1 = idle, 0 = saturated or inside an engine-429 window).
backend_capacity_slots = Gauge(
    "tpu_router:backend_capacity_slots",
    "Learned max useful concurrency per backend (capacity model)",
    ["server"],
)
backend_capacity_score = Gauge(
    "tpu_router:backend_capacity_score",
    "Free-capacity fraction per backend (0 = saturated, 1 = idle)",
    ["server"],
)

# -- fleet prefix-popularity view (routing logic kv_aware_popularity) ------
# Prefixes promoted to HOT (decayed request frequency crossed the
# threshold; each one is served by a replica set from then on).
prefix_hot_total = Counter(
    "tpu_router:prefix_hot_total",
    "Prefixes promoted to hot by the popularity view (replica-set serving)",
)
# Largest live replica set across hot prefixes — the shared system
# prompt's replication degree.  1 under light load (no replication
# needed), grows toward --kv-popularity-max-replicas as the owner pool
# saturates, shrinks back by TTL decay.
prefix_replica_set_size = Gauge(
    "tpu_router:prefix_replica_set_size",
    "Largest live hot-prefix replica set (popularity view)",
)
# Fleet-wide token-weighted KV prefix hit rate, computed from the
# engines' scraped tpu:prefix_cache_{hit,query}_tokens_total truth
# counters — the BASELINE.md north-star KV metric, at one scrape point.
fleet_prefix_hit_rate = Gauge(
    "tpu_router:fleet_prefix_hit_rate",
    "Fleet-wide prefix-cache hit rate (sum scraped hit/query tokens)",
)

# -- disaggregated prefill/decode serving (routing policy `disagg`) --------
# Handoff latency: the whole prefill phase as the router sees it — prime
# connect + engine prefill + eager chain export + handoff-token response.
# Decode-phase admission happens inside this budget's shadow, so p95 here
# IS the TTFT tax disaggregation pays for interference-free decode.
disagg_handoff_seconds = Histogram(
    "tpu_router:disagg_handoff_seconds",
    "Disagg prefill-phase (prime + eager export + handoff) latency",
    buckets=(0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0),
)
# Why a two-phase request degraded to the fused single-backend path.
# Closed reason set, pre-seeded below so dashboards and rate() see stable
# label sets from boot (the same contract as the engine's labeled
# fallback counter).
DISAGG_FALLBACK_REASONS = (
    "prefill_pool_empty",   # no prefill-role backends discovered/healthy
    "prefill_breaker_open", # prefill pool exists but every breaker is open
    "decode_pool_empty",    # no decode-capable backend for phase 2
    "prime_failed",         # prime call errored/timed out/was shed
    "handoff_unexported",   # prime ran but the engine had no store to export to
    "prefix_miss",          # decode-side prefetch missed; decode recomputed
)
disagg_fallback_total = Counter(
    "tpu_router:disagg_fallback_total",
    "Two-phase disagg requests degraded to the fused path, by reason",
    ["reason"],
)
for _reason in DISAGG_FALLBACK_REASONS:
    disagg_fallback_total.labels(reason=_reason)
# Per-role routed-request accounting: every completion the disagg policy
# handled lands here once per phase it actually routed ("prefill" for the
# prime, "decode" for the generation, "fused" when it degraded).
disagg_requests_total = Counter(
    "tpu_router:disagg_requests_total",
    "Requests routed by the disagg policy, by phase role",
    ["role"],
)
for _role in ("prefill", "decode", "fused"):
    disagg_requests_total.labels(role=_role)
