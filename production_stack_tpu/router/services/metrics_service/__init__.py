"""Router-level Prometheus gauges, labeled by backend server.

Reference counterpart: src/vllm_router/services/metrics_service/__init__.py:1-31.
Extended with the gauges the reference's dashboard charts but never exports
(router queueing delay) and TPU engine mirrors (HBM KV usage, prefix hit
rate) so one scrape of the router suffices for the whole stack.
"""

from prometheus_client import Counter, Gauge

current_qps = Gauge("tpu_router:current_qps", "Sliding-window QPS", ["server"])
avg_ttft = Gauge("tpu_router:avg_ttft", "Average time-to-first-token (s)", ["server"])
avg_latency = Gauge(
    "tpu_router:avg_latency", "Average end-to-end request latency (s)", ["server"]
)
avg_itl = Gauge("tpu_router:avg_itl", "Average inter-token latency (s)", ["server"])
avg_decoding_length = Gauge(
    "tpu_router:avg_decoding_length", "Average streamed chunks per request", ["server"]
)
queueing_delay = Gauge(
    "tpu_router:queueing_delay_seconds",
    "Router-side queueing delay: receive -> backend connect (s)",
    ["server"],
)
num_prefill_requests = Gauge(
    "tpu_router:num_prefill_requests", "Requests awaiting first token", ["server"]
)
num_decoding_requests = Gauge(
    "tpu_router:num_decoding_requests", "Requests streaming tokens", ["server"]
)
num_requests_finished = Gauge(
    "tpu_router:num_requests_finished", "Completed requests", ["server"]
)
num_requests_uncompleted = Gauge(
    "tpu_router:num_requests_uncompleted", "In-flight requests", ["server"]
)
healthy_pods_total = Gauge(
    "tpu_router:healthy_pods_total", "Healthy serving-engine endpoints", ["model"]
)
# Engine-side mirrors (scraped via EngineStatsScraper).
engine_kv_usage_perc = Gauge(
    "tpu_router:engine_hbm_kv_usage_perc", "Engine TPU HBM KV pool usage (0-1)", ["server"]
)
engine_prefix_cache_hit_rate = Gauge(
    "tpu_router:engine_prefix_cache_hit_rate", "Engine prefix-cache hit rate (0-1)", ["server"]
)
engine_queue_depth = Gauge(
    "tpu_router:engine_num_requests_waiting", "Engine waiting-queue depth", ["server"]
)
# Overload protection (docs/robustness.md).
circuit_state = Gauge(
    "tpu_router:circuit_state",
    "Per-backend circuit breaker state (0=closed, 1=half_open, 2=open)",
    ["server"],
)
deadline_expired_total = Counter(
    "tpu_router:deadline_expired_total",
    "Requests shed by the router because their deadline expired before "
    "(or during) backend connect",
)
