"""Router services (reference counterpart: src/vllm_router/services/)."""
