"""OpenAI Batch API: SQLite-backed queue + background processor that
executes every batch line through the router's real routing/proxy stack.

Reference counterpart: src/vllm_router/services/batch_service/
(BatchInfo batch.py:6-91, LocalBatchProcessor local_processor.py:19-208).
The reference's processor never executes anything — its body is a
simulation stub (local_processor.py:179-195, "simulate processing" sleep +
canned output).  Here each input line is routed exactly like a live
request: model-filtered endpoints -> routing logic -> POST to the chosen
engine, with bounded concurrency, per-line error capture into an OpenAI
error file, and request_counts bookkeeping.

aiosqlite is not available on TPU images; sqlite3 runs in worker threads
(one short-lived connection per operation — the queue is low-QPS control
plane, not a data path).
"""

from __future__ import annotations

import asyncio
import dataclasses
import enum
import json
import logging
import os
import sqlite3
import time
import uuid
from typing import Any, Dict, List, Optional

from production_stack_tpu.router.routing import ROUTING_SERVICE
from production_stack_tpu.router.service_discovery import DISCOVERY_SERVICE
from production_stack_tpu.router.services.files_service import FILE_STORAGE, Storage
from production_stack_tpu.router.services.request_service.request import (
    CLIENT_SESSION,
    ENGINE_STATS_SCRAPER,
    REQUEST_STATS_MONITOR,
)

logger = logging.getLogger(__name__)

BATCH_PROCESSOR = "batch_processor"


class BatchStatus(str, enum.Enum):
    """OpenAI batch lifecycle (the reference uses pending/running;
    we emit the OpenAI status vocabulary for client compatibility)."""

    VALIDATING = "validating"
    IN_PROGRESS = "in_progress"
    FINALIZING = "finalizing"
    COMPLETED = "completed"
    FAILED = "failed"
    CANCELLED = "cancelled"


BATCH_ENDPOINTS = ("/v1/chat/completions", "/v1/completions", "/v1/embeddings")


@dataclasses.dataclass
class BatchInfo:
    """OpenAI batch object
    (https://platform.openai.com/docs/api-reference/batch/object)."""

    id: str
    status: BatchStatus
    input_file_id: str
    endpoint: str
    completion_window: str
    created_at: int
    output_file_id: Optional[str] = None
    error_file_id: Optional[str] = None
    in_progress_at: Optional[int] = None
    completed_at: Optional[int] = None
    failed_at: Optional[int] = None
    cancelled_at: Optional[int] = None
    total_requests: int = 0
    completed_requests: int = 0
    failed_requests: int = 0
    metadata: Optional[Dict[str, Any]] = None

    def to_dict(self) -> dict:
        return {
            "id": self.id,
            "object": "batch",
            "endpoint": self.endpoint,
            "input_file_id": self.input_file_id,
            "completion_window": self.completion_window,
            "status": self.status.value,
            "output_file_id": self.output_file_id,
            "error_file_id": self.error_file_id,
            "created_at": self.created_at,
            "in_progress_at": self.in_progress_at,
            "completed_at": self.completed_at,
            "failed_at": self.failed_at,
            "cancelled_at": self.cancelled_at,
            "request_counts": {
                "total": self.total_requests,
                "completed": self.completed_requests,
                "failed": self.failed_requests,
            },
            "metadata": self.metadata,
        }


_COLUMNS = (
    "batch_id, status, input_file_id, endpoint, completion_window, created_at, "
    "output_file_id, error_file_id, in_progress_at, completed_at, failed_at, "
    "cancelled_at, total_requests, completed_requests, failed_requests, metadata"
)


def _row_to_info(row) -> BatchInfo:
    return BatchInfo(
        id=row[0],
        status=BatchStatus(row[1]),
        input_file_id=row[2],
        endpoint=row[3],
        completion_window=row[4],
        created_at=row[5],
        output_file_id=row[6],
        error_file_id=row[7],
        in_progress_at=row[8],
        completed_at=row[9],
        failed_at=row[10],
        cancelled_at=row[11],
        total_requests=row[12],
        completed_requests=row[13],
        failed_requests=row[14],
        metadata=json.loads(row[15]) if row[15] else None,
    )


class _BatchRequestStub:
    """Duck-typed routing.base.Request for batch-originated requests."""

    def __init__(self, headers: Dict[str, str]):
        self.headers = headers


class LocalBatchProcessor:
    """SQLite queue + poller task (reference local_processor.py:19-208,
    with real execution instead of the simulation stub)."""

    def __init__(
        self,
        db_dir: str,
        storage: Storage,
        registry,
        poll_interval: float = 1.0,
        max_concurrency: int = 8,
    ):
        os.makedirs(db_dir, exist_ok=True)
        self.db_path = os.path.join(db_dir, "batch_queue.db")
        self.storage = storage
        self.registry = registry
        self.poll_interval = poll_interval
        self.max_concurrency = max_concurrency
        self._task: Optional[asyncio.Task] = None
        self._setup()

    # -- sqlite plumbing (worker threads) ----------------------------------

    def _setup(self) -> None:
        with sqlite3.connect(self.db_path) as db:
            db.execute(
                "CREATE TABLE IF NOT EXISTS batch_queue ("
                "batch_id TEXT PRIMARY KEY, status TEXT, input_file_id TEXT, "
                "endpoint TEXT, completion_window TEXT, created_at INTEGER, "
                "output_file_id TEXT, error_file_id TEXT, in_progress_at INTEGER, "
                "completed_at INTEGER, failed_at INTEGER, cancelled_at INTEGER, "
                "total_requests INTEGER DEFAULT 0, "
                "completed_requests INTEGER DEFAULT 0, "
                "failed_requests INTEGER DEFAULT 0, metadata TEXT)"
            )

    async def _db(self, fn):
        def run():
            with sqlite3.connect(self.db_path) as db:
                return fn(db)

        return await asyncio.to_thread(run)

    async def _write_info(self, info: BatchInfo) -> None:
        values = (
            info.id, info.status.value, info.input_file_id, info.endpoint,
            info.completion_window, info.created_at, info.output_file_id,
            info.error_file_id, info.in_progress_at, info.completed_at,
            info.failed_at, info.cancelled_at, info.total_requests,
            info.completed_requests, info.failed_requests,
            json.dumps(info.metadata) if info.metadata else None,
        )
        placeholders = ",".join("?" * 16)
        await self._db(
            lambda db: db.execute(
                f"INSERT OR REPLACE INTO batch_queue ({_COLUMNS}) "
                f"VALUES ({placeholders})",
                values,
            )
        )

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> None:
        if self._task is None:
            self._task = asyncio.create_task(self._poll_loop())

    async def close(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    # -- API ---------------------------------------------------------------

    async def create_batch(
        self,
        input_file_id: str,
        endpoint: str,
        completion_window: str = "24h",
        metadata: Optional[dict] = None,
    ) -> BatchInfo:
        if endpoint not in BATCH_ENDPOINTS:
            raise ValueError(
                f"Unsupported batch endpoint {endpoint!r}; supported: {BATCH_ENDPOINTS}"
            )
        info = BatchInfo(
            id="batch_" + uuid.uuid4().hex[:12],
            status=BatchStatus.VALIDATING,
            input_file_id=input_file_id,
            endpoint=endpoint,
            completion_window=completion_window,
            created_at=int(time.time()),
            metadata=metadata,
        )
        await self._write_info(info)
        logger.info("Created batch %s (input %s)", info.id, input_file_id)
        return info

    async def retrieve_batch(self, batch_id: str) -> BatchInfo:
        row = await self._db(
            lambda db: db.execute(
                f"SELECT {_COLUMNS} FROM batch_queue WHERE batch_id = ?",
                (batch_id,),
            ).fetchone()
        )
        if row is None:
            raise FileNotFoundError(batch_id)
        return _row_to_info(row)

    async def list_batches(
        self, limit: int = 20, after: Optional[str] = None
    ) -> List[BatchInfo]:
        def query(db):
            if after:
                anchor = db.execute(
                    "SELECT created_at FROM batch_queue WHERE batch_id = ?",
                    (after,),
                ).fetchone()
                if anchor is None:
                    return []
                return db.execute(
                    f"SELECT {_COLUMNS} FROM batch_queue WHERE created_at <= ? "
                    "AND batch_id != ? ORDER BY created_at DESC, batch_id LIMIT ?",
                    (anchor[0], after, limit),
                ).fetchall()
            return db.execute(
                f"SELECT {_COLUMNS} FROM batch_queue "
                "ORDER BY created_at DESC, batch_id LIMIT ?",
                (limit,),
            ).fetchall()

        return [_row_to_info(r) for r in await self._db(query)]

    async def cancel_batch(self, batch_id: str) -> BatchInfo:
        info = await self.retrieve_batch(batch_id)
        if info.status in (BatchStatus.VALIDATING, BatchStatus.IN_PROGRESS):
            # Conditional UPDATE: if the processor finished the batch between
            # our read and this write, COMPLETED must win — a blanket
            # REPLACE would orphan the output/error files.
            await self._db(
                lambda db: db.execute(
                    "UPDATE batch_queue SET status = ?, cancelled_at = ? "
                    "WHERE batch_id = ? AND status IN (?, ?)",
                    (
                        BatchStatus.CANCELLED.value, int(time.time()), batch_id,
                        BatchStatus.VALIDATING.value, BatchStatus.IN_PROGRESS.value,
                    ),
                )
            )
            info = await self.retrieve_batch(batch_id)
        return info

    # -- processing --------------------------------------------------------

    async def _poll_loop(self) -> None:
        while True:
            try:
                row = await self._db(
                    lambda db: db.execute(
                        f"SELECT {_COLUMNS} FROM batch_queue WHERE status = ? "
                        "ORDER BY created_at LIMIT 1",
                        (BatchStatus.VALIDATING.value,),
                    ).fetchone()
                )
                if row is not None:
                    await self._process_batch(_row_to_info(row))
                    continue  # drain the queue before sleeping
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.exception("batch poll loop error")
            await asyncio.sleep(self.poll_interval)

    async def _process_batch(self, info: BatchInfo) -> None:
        logger.info("Processing batch %s", info.id)
        try:
            content = await self.storage.get_file_content(info.input_file_id)
        except FileNotFoundError:
            info.status = BatchStatus.FAILED
            info.failed_at = int(time.time())
            await self._write_info(info)
            return

        lines = [ln for ln in content.decode("utf-8").splitlines() if ln.strip()]
        info.status = BatchStatus.IN_PROGRESS
        info.in_progress_at = int(time.time())
        info.total_requests = len(lines)
        # Conditional transition: a cancel that landed between the poller's
        # SELECT and this write must win (stay CANCELLED), not be overwritten
        # back to IN_PROGRESS.
        claimed = await self._db(
            lambda db: db.execute(
                "UPDATE batch_queue SET status = ?, in_progress_at = ?, "
                "total_requests = ? WHERE batch_id = ? AND status = ?",
                (
                    info.status.value, info.in_progress_at, info.total_requests,
                    info.id, BatchStatus.VALIDATING.value,
                ),
            ).rowcount
        )
        if not claimed:
            logger.info("Batch %s no longer pending (cancelled?); skipping", info.id)
            return

        try:
            await self._run_claimed_batch(info, lines)
        except asyncio.CancelledError:
            raise
        except Exception:
            # Without this, any post-claim error wedges the batch in
            # IN_PROGRESS forever (the poller only selects VALIDATING rows).
            logger.exception("Batch %s failed", info.id)
            await self._db(
                lambda db: db.execute(
                    "UPDATE batch_queue SET status = ?, failed_at = ? "
                    "WHERE batch_id = ? AND status IN (?, ?)",
                    (
                        BatchStatus.FAILED.value, int(time.time()), info.id,
                        BatchStatus.IN_PROGRESS.value, BatchStatus.FINALIZING.value,
                    ),
                )
            )

    async def _run_claimed_batch(self, info: BatchInfo, lines: List[str]) -> None:
        semaphore = asyncio.Semaphore(self.max_concurrency)
        cancelled = asyncio.Event()

        async def watch_cancel():
            # One row read per poll interval (not per line) keeps the stop
            # latency bounded without O(lines) sqlite hops.
            while not cancelled.is_set():
                current = await self.retrieve_batch(info.id)
                if current.status == BatchStatus.CANCELLED:
                    cancelled.set()
                    return
                await asyncio.sleep(self.poll_interval)

        async def run_line(idx: int, line: str):
            async with semaphore:
                if cancelled.is_set():
                    return None
                return await self._execute_line(info, idx, line)

        watcher = asyncio.create_task(watch_cancel())
        try:
            results = [
                r for r in await asyncio.gather(
                    *(run_line(i, line) for i, line in enumerate(lines))
                )
                if r is not None
            ]
        finally:
            cancelled.set()
            watcher.cancel()
            try:
                await watcher
            except (asyncio.CancelledError, Exception):
                # A watcher that died of e.g. a transient sqlite error must
                # not mask the batch result.
                pass

        # Conditional IN_PROGRESS -> FINALIZING: a cancel landing any time
        # after the claim must stay terminal.
        info.status = BatchStatus.FINALIZING
        advanced = await self._db(
            lambda db: db.execute(
                "UPDATE batch_queue SET status = ? "
                "WHERE batch_id = ? AND status = ?",
                (
                    BatchStatus.FINALIZING.value, info.id,
                    BatchStatus.IN_PROGRESS.value,
                ),
            ).rowcount
        )
        if not advanced:
            return

        outputs = [json.dumps(r) + "\n" for r in results if "response" in r]
        errors = [json.dumps(r) + "\n" for r in results if "error" in r]
        info.completed_requests = len(outputs)
        info.failed_requests = len(errors)
        if outputs:
            out_file = await self.storage.save_file(
                file_name=f"{info.id}_output.jsonl",
                content="".join(outputs).encode(),
                purpose="batch_output",
            )
            info.output_file_id = out_file.id
        if errors:
            err_file = await self.storage.save_file(
                file_name=f"{info.id}_errors.jsonl",
                content="".join(errors).encode(),
                purpose="batch_output",
            )
            info.error_file_id = err_file.id
        info.status = BatchStatus.COMPLETED
        info.completed_at = int(time.time())
        # FINALIZING -> COMPLETED, again conditionally (cancel can't land in
        # FINALIZING via cancel_batch, but stay single-writer-safe anyway).
        await self._db(
            lambda db: db.execute(
                "UPDATE batch_queue SET status = ?, completed_at = ?, "
                "output_file_id = ?, error_file_id = ?, "
                "completed_requests = ?, failed_requests = ? "
                "WHERE batch_id = ? AND status = ?",
                (
                    info.status.value, info.completed_at, info.output_file_id,
                    info.error_file_id, info.completed_requests,
                    info.failed_requests, info.id, BatchStatus.FINALIZING.value,
                ),
            )
        )
        logger.info(
            "Batch %s done: %d ok, %d failed",
            info.id, info.completed_requests, info.failed_requests,
        )

    async def _execute_line(self, info: BatchInfo, idx: int, line: str) -> dict:
        """Route and execute one batch input line through the live stack
        (the step the reference stubs out, local_processor.py:179-195)."""
        base = {"id": f"{info.id}_{idx}", "custom_id": None}
        try:
            item = json.loads(line)
        except json.JSONDecodeError as e:
            return {**base, "error": {"code": "invalid_json", "message": str(e)}}
        if not isinstance(item, dict):
            return {**base, "error": {
                "code": "invalid_request",
                "message": "each batch line must be a JSON object",
            }}
        base["custom_id"] = item.get("custom_id")
        body = item.get("body") or {}
        url_path = item.get("url") or info.endpoint
        model = body.get("model")

        discovery = self.registry.get(DISCOVERY_SERVICE)
        router = self.registry.get(ROUTING_SERVICE)
        session = self.registry.get(CLIENT_SESSION)
        if discovery is None or router is None or session is None:
            return {**base, "error": {"code": "router_not_ready", "message": "router services unavailable"}}

        endpoints = [ep for ep in discovery.get_endpoint_info() if not ep.sleep]
        scraper = self.registry.get(ENGINE_STATS_SCRAPER)
        if scraper is not None:
            unreachable = scraper.get_unreachable_urls()
            reachable = [ep for ep in endpoints if ep.url not in unreachable]
            if reachable:
                endpoints = reachable
        if model is not None:
            endpoints = [
                ep for ep in endpoints
                if not ep.model_names or model in ep.model_names
            ]
        engine_stats = scraper.get_engine_stats() if scraper else {}
        monitor = self.registry.get(REQUEST_STATS_MONITOR)
        request_stats = monitor.get_request_stats(time.time()) if monitor else {}
        try:
            server_url = router.route_request(
                endpoints, engine_stats, request_stats,
                _BatchRequestStub(headers={}), body,
            )
        except ValueError as e:
            return {**base, "error": {"code": "no_backend", "message": str(e)}}

        request_id = f"{info.id}-{idx}"
        if monitor:
            monitor.on_new_request(server_url, request_id, time.time())
        try:
            async with session.post(
                f"{server_url}{url_path}", json=body,
                headers={"x-request-id": request_id},
            ) as resp:
                if monitor:
                    monitor.on_backend_connected(server_url, request_id, time.time())
                payload = await resp.read()
                if monitor:
                    monitor.on_request_response(server_url, request_id, time.time())
                    monitor.on_request_complete(server_url, request_id, time.time())
                try:
                    parsed = json.loads(payload)
                except json.JSONDecodeError:
                    parsed = payload.decode("utf-8", "replace")
                if resp.status >= 400:
                    return {
                        **base,
                        "error": {"code": f"http_{resp.status}", "message": parsed},
                    }
                return {
                    **base,
                    "response": {"status_code": resp.status, "body": parsed},
                }
        except Exception as e:
            if monitor:
                monitor.on_request_failed(server_url, request_id, time.time())
            return {**base, "error": {"code": "request_failed", "message": str(e)}}


def initialize_batch_service(app, registry, args) -> None:
    """Wire storage + processor (called from app.initialize_all when
    --enable-batch-api is set)."""
    from production_stack_tpu.router.services.files_service import LocalFileStorage

    storage = LocalFileStorage(args.file_storage_path)
    registry.set(FILE_STORAGE, storage)
    processor = LocalBatchProcessor(
        db_dir=args.file_storage_path,
        storage=storage,
        registry=registry,
    )
    registry.set(BATCH_PROCESSOR, processor)
