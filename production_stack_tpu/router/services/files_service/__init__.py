"""OpenAI Files API storage (local disk).

Reference counterpart: src/vllm_router/services/files_service/
(Storage ABC storage.py:7-157, FileStorage file_storage.py:14-120,
OpenAIFile openai_files.py:5-48).

Differences from the reference:

* Metadata (filename, purpose, created_at) persists in a sidecar JSON, so
  file listings survive router restarts (the reference loses filenames).
* list_files is part of the storage interface (the reference ABC declares
  it but the OpenAI list endpoint was never wired).
"""

from __future__ import annotations

import asyncio
import dataclasses
import json
import os
import time
import uuid
from typing import List, Optional

FILE_STORAGE = "file_storage"

DEFAULT_USER_ID = "default"


@dataclasses.dataclass
class OpenAIFile:
    """OpenAI file object (https://platform.openai.com/docs/api-reference/files/object)."""

    id: str
    bytes: int
    created_at: int
    filename: str
    purpose: str
    object: str = "file"

    def metadata(self) -> dict:
        return dataclasses.asdict(self)


class Storage:
    """Interface (reference storage.py:7-139)."""

    async def save_file(
        self,
        file_name: str,
        content: bytes,
        purpose: str = "batch",
        file_id: Optional[str] = None,
        user_id: str = DEFAULT_USER_ID,
    ) -> OpenAIFile:
        raise NotImplementedError

    async def get_file(self, file_id: str, user_id: str = DEFAULT_USER_ID) -> OpenAIFile:
        raise NotImplementedError

    async def get_file_content(
        self, file_id: str, user_id: str = DEFAULT_USER_ID
    ) -> bytes:
        raise NotImplementedError

    async def list_files(self, user_id: str = DEFAULT_USER_ID) -> List[OpenAIFile]:
        raise NotImplementedError

    async def delete_file(self, file_id: str, user_id: str = DEFAULT_USER_ID) -> None:
        raise NotImplementedError


class LocalFileStorage(Storage):
    """Local-disk store: ``<base>/<user>/<file_id>`` + ``<file_id>.json``
    metadata sidecar.  IO runs in a worker thread (files can be large;
    the event loop must not block — reference uses aiofiles for the same
    reason, file_storage.py:52)."""

    def __init__(self, base_path: str = "/tmp/tpu_router_storage"):
        self.base_path = base_path
        os.makedirs(base_path, exist_ok=True)

    def _user_dir(self, user_id: str) -> str:
        path = os.path.join(self.base_path, user_id)
        os.makedirs(path, exist_ok=True)
        return path

    def _paths(self, file_id: str, user_id: str):
        base = os.path.join(self._user_dir(user_id), file_id)
        return base, base + ".json"

    async def save_file(
        self,
        file_name: str,
        content: bytes,
        purpose: str = "batch",
        file_id: Optional[str] = None,
        user_id: str = DEFAULT_USER_ID,
    ) -> OpenAIFile:
        if content is None:
            raise ValueError("content cannot be None")
        file_id = file_id or f"file-{uuid.uuid4().hex[:12]}"
        if "/" in file_id or file_id.startswith("."):
            raise ValueError(f"invalid file id {file_id!r}")
        info = OpenAIFile(
            id=file_id,
            bytes=len(content),
            created_at=int(time.time()),
            filename=file_name or file_id,
            purpose=purpose,
        )
        content_path, meta_path = self._paths(file_id, user_id)

        def write():
            with open(content_path, "wb") as f:
                f.write(content)
            with open(meta_path, "w") as f:
                json.dump(info.metadata(), f)

        await asyncio.to_thread(write)
        return info

    async def get_file(self, file_id: str, user_id: str = DEFAULT_USER_ID) -> OpenAIFile:
        _, meta_path = self._paths(file_id, user_id)

        def read():
            with open(meta_path) as f:
                return json.load(f)

        try:
            return OpenAIFile(**await asyncio.to_thread(read))
        except OSError:
            raise FileNotFoundError(file_id)

    async def get_file_content(
        self, file_id: str, user_id: str = DEFAULT_USER_ID
    ) -> bytes:
        content_path, _ = self._paths(file_id, user_id)

        def read():
            with open(content_path, "rb") as f:
                return f.read()

        try:
            return await asyncio.to_thread(read)
        except OSError:
            raise FileNotFoundError(file_id)

    async def list_files(self, user_id: str = DEFAULT_USER_ID) -> List[OpenAIFile]:
        user_dir = self._user_dir(user_id)

        def read_all():
            out = []
            for name in sorted(os.listdir(user_dir)):
                if not name.endswith(".json"):
                    continue
                try:
                    with open(os.path.join(user_dir, name)) as f:
                        out.append(OpenAIFile(**json.load(f)))
                except (OSError, TypeError, ValueError):
                    continue
            return out

        return await asyncio.to_thread(read_all)

    async def delete_file(self, file_id: str, user_id: str = DEFAULT_USER_ID) -> None:
        content_path, meta_path = self._paths(file_id, user_id)

        def rm():
            found = False
            for path in (content_path, meta_path):
                try:
                    os.remove(path)
                    found = True
                except OSError:
                    pass
            if not found:
                raise FileNotFoundError(file_id)

        await asyncio.to_thread(rm)
