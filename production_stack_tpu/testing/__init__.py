"""Test doubles: fake TPU serving engine (reference: src/tests/perftest/)."""
