"""Fake TPU serving engine: SSE token streaming + TPU-vocabulary /metrics.

Reference counterpart: src/tests/perftest/fake-openai-server.py:50-171 — the
stand-in backend that makes the whole stack testable without accelerators
(SURVEY.md section 4 takeaway).  Ours emits the ``tpu:`` metric vocabulary
our scraper/dashboard/HPA key off, simulates a configurable TTFT and
tokens/s, and tracks running-request gauges so load-aware routing is
exercisable in CI.

Usable three ways: as an importable aiohttp app factory (unit tests), as a
CLI (perf tests / CI workflows), and inside the helm chart's clusterless CI
values as a stand-in engine image command.
"""

from __future__ import annotations

import argparse
import asyncio
import hashlib
import json
import random
import time
import uuid

from aiohttp import web

from production_stack_tpu.obs.engine import EngineObs
from production_stack_tpu.obs.histogram import Histogram, render_histogram
from production_stack_tpu.obs.trace import parse_traceparent
from production_stack_tpu.router.stats import vocabulary as vocab


class FakeSliceGroup:
    """Simulated multi-host slice group behind ONE fake leader endpoint
    (docs/robustness.md "Slice lifecycle contract", jax-free).

    Mirrors the real contract exactly enough for the router/fleet plane
    to be chaos-tested in tier-1: followers "ack" continuously while
    alive; :meth:`kill_member` freezes a member's acks, so after
    ``member_timeout_s`` the leader's /health fails (the slice is ONE
    endpoint whose health is the conjunction of its members) and the
    data plane starts refusing connections (the leader fatal-exits in
    production).  :meth:`restart` models the parallel k8s group restart:
    a STRICTLY larger epoch, members revived, drains cleared.  A
    follower's POST /drain relays to the leader — the leader drains the
    whole group.
    """

    def __init__(
        self,
        num_members: int = 4,
        member_timeout_s: float = 1.0,
        clock=time.monotonic,
    ):
        from production_stack_tpu.engine.parallel.distributed import new_epoch

        self._new_epoch = new_epoch
        self.num_members = int(num_members)
        self.member_timeout_s = float(member_timeout_s)
        self._clock = clock
        self.epoch = new_epoch()
        self._last_ack = {
            pid: clock() for pid in range(1, self.num_members)
        }
        self._killed: set = set()
        self._problem: str | None = None
        self.member_failures: dict = {}  # reason -> count
        self.drain_relays = 0
        self.drain_relayed = False
        self.restarts = 0

    def member_ack_ages(self) -> dict:
        """Live members ack continuously (age ~0); killed members' ages
        grow in real time — the tpu:lockstep_member_last_ack_seconds
        truth the leader exports."""
        now = self._clock()
        for pid in self._last_ack:
            if pid not in self._killed:
                self._last_ack[pid] = now
        return {pid: max(0.0, now - t) for pid, t in self._last_ack.items()}

    def kill_member(self, pid: int) -> None:
        if pid not in self._last_ack:
            raise ValueError(f"no such member ordinal {pid}")
        self._killed.add(pid)

    def problem(self) -> str | None:
        """Non-None once any member has been silent past the timeout
        (first detection counts one member_silent failure, like the real
        GroupLivenessMonitor)."""
        if self._problem is None:
            for pid, age in self.member_ack_ages().items():
                if age > self.member_timeout_s:
                    self._problem = (
                        f"slice member {pid} silent for {age:.1f}s "
                        f"(member timeout {self.member_timeout_s:.1f}s)"
                    )
                    self.member_failures["member_silent"] = (
                        self.member_failures.get("member_silent", 0) + 1
                    )
                    break
        return self._problem

    def relay_drain(self, pid: int) -> None:
        self.drain_relays += 1
        self.drain_relayed = True

    def restart(self) -> None:
        """The parallel group restart k8s performs after a failure: every
        member comes back into ONE fresh incarnation whose epoch is
        strictly larger — a restarted member can never replay into it."""
        self.epoch = self._new_epoch()
        assert self.epoch > 0
        self._killed.clear()
        self._problem = None
        self.drain_relayed = False
        now = self._clock()
        for pid in self._last_ack:
            self._last_ack[pid] = now
        self.restarts += 1


class FakeEngineState:
    def __init__(
        self,
        model: str = "fake/llama-3-8b",
        tokens_per_sec: float = 500.0,
        ttft: float = 0.02,
        max_tokens_default: int = 100,
        seed: int = 0,
        capacity: int | None = None,
        max_queued: int = 0,
        admission_control: bool = True,
        disagg_role: str | None = None,
        shared_store: set | None = None,
        prefetch_outcome: str | None = None,
        prefix_chunk_chars: int = 64,
        prefill_chars_per_sec: float | None = None,
        prefill_scales_with_load: bool = False,
        remote_store_import: bool = False,
        store_import_chars_per_sec: float | None = None,
        slice_group: FakeSliceGroup | None = None,
        simulate_compiles: bool = False,
        tracing: bool = True,
        max_queued_encode_texts: int = 256,
    ):
        self.model = model
        self.tokens_per_sec = tokens_per_sec
        self.ttft = ttft
        self.max_tokens_default = max_tokens_default
        self.num_running = 0
        self.num_waiting = 0
        self.total_requests = 0
        self.total_model_probes = 0  # GETs of /v1/models (discovery probes)
        self.total_prompt_tokens = 0
        self.total_generated_tokens = 0  # bumped per emitted token
        self.total_finished = 0  # bumped at completion (real-engine semantics)
        # -- prefix-cache simulation (chunk-chain granularity) -------------
        # ``note_prompt`` walks the prompt's chained chunk digests
        # (fake_prefix_chain) against the set this engine has "cached":
        # the matched leading run counts as hit tokens, the rest as cold
        # prefill — the same token-weighted accounting the real engine's
        # BlockPool keeps, so fleet KV hit rates measured against fakes
        # respond to routing affinity the way real engines do.
        self.prefix_chunk_chars = int(prefix_chunk_chars)
        self.prefix_hit_tokens = 0
        self.prefix_query_tokens = 0
        # Prefill cost model: with ``prefill_chars_per_sec`` set, TTFT
        # grows with the UNCACHED prompt tail (cold prefill); with
        # ``prefill_scales_with_load`` + capacity, it additionally
        # stretches with oversubscription (prefill queueing).  Both
        # default off, preserving the constant-TTFT legacy fake exactly.
        self.prefill_chars_per_sec = prefill_chars_per_sec
        self.prefill_scales_with_load = bool(prefill_scales_with_load)
        # Remote-store warming (the PR-4 plane, simulated): computed
        # chunks are exported to ``shared_store`` and store-resident
        # chunks import instead of recomputing (a cache hit at a cheaper
        # per-char cost) — how a popularity-grown replica warms a hot
        # prefix without paying the full prefill.
        self.remote_store_import = bool(remote_store_import)
        self.store_import_chars_per_sec = store_import_chars_per_sec
        self._rng = random.Random(seed)
        self._seen_chunks: set = set()
        # Same obs contract as the real engine (EngineObs): tracing tests
        # and the bench trace_report run against this in CI.  tracing=False
        # mirrors obs.tracing=off — the recorder/tracker zero-state gate.
        self.obs = EngineObs(enabled=tracing)
        # Simulated XLA compiles: a cold prompt-size bucket records one
        # compile event (first request of each pow2 size pays it, repeats
        # don't — the real cache-growth semantics), taints the request's
        # trace/window, and stamps '"compile": true' into the first
        # response chunk exactly like the real server, so the router's
        # compile-excluded TTFT path and /debug/compiles are CI-testable
        # without jax.
        self.simulate_compiles = bool(simulate_compiles)
        # Headers of the most recent completion request (trace-propagation
        # assertions in tests).
        self.last_headers: dict = {}
        # -- overload / lifecycle model (docs/robustness.md) ---------------
        # ``capacity`` models max_num_seqs: with it set, per-token
        # intervals scale with in-flight/capacity (a deterministic
        # oversubscription-degrades-ITL model — the signal the
        # shed-vs-no-shed tier-1 test measures without a TPU), and
        # bounded admission 429s once in-flight exceeds
        # capacity + max_queued.  capacity=None keeps the legacy
        # constant-rate fake exactly.
        self.capacity = capacity
        self.max_queued = max_queued
        self.admission_control = admission_control
        self.admission_rejected = 0  # tpu:admission_rejected_total
        self.deadline_expired = 0  # tpu:deadline_expired_total
        # Deterministic fault-injection surface (FakeEngineState.inject):
        # kind -> params.  Counted kinds decrement per use; count=-1 means
        # "until cleared".
        self.injections: dict = {}
        # Request ids whose handler was torn down mid-stream (client/router
        # disconnect or cancellation) — the abort-propagation assertions.
        self.aborted_requests: list = []
        self.draining = False
        # Completion-handler entries BEFORE any injection fires: counts
        # every connection the router actually made (the breaker tests'
        # "an open backend receives no traffic" assertion).
        self.data_plane_hits = 0
        # -- disaggregated prefill/decode emulation (--disagg-role) --------
        # Same contract as the real engine (docs/engine.md): a prefill
        # prime (x-disagg-phase: prefill) returns a handoff token and
        # records the chain export; a handoff-tagged generation
        # (x-disagg-handoff) simulates the prefetch — a hit skips the
        # TTFT sleep (the prompt was imported, decode runs no prompt
        # tokens) and stamps X-Disagg-Prefix.  ``shared_store`` is the
        # simulated shared KV store: pass ONE set to every fake in a
        # fleet so prefill-pool exports are visible to decode-pool fakes.
        if disagg_role not in (None, "prefill", "decode", "both", "encode"):
            raise ValueError(f"unknown disagg_role {disagg_role!r}")
        self.disagg_role = disagg_role
        self.shared_store = shared_store if shared_store is not None else set()
        # Force the decode-phase outcome ("hit"/"miss") regardless of the
        # store — the prefetch-miss fallback tests key on this.
        self.prefetch_outcome = prefetch_outcome
        self.exports: list = []  # recorded prime exports (chains)
        self.disagg_prefill_primes = 0
        self.disagg_handoff_hits = 0
        self.disagg_handoff_misses = 0
        # -- encode lane emulation (embeddings / rerank / score) -----------
        # Same contract as the real engine's batched encode lane
        # (engine/server/encode_batcher.py): each request lands as ONE
        # batch, deterministic unit vectors keyed by text alone (so any
        # two fakes — or two scrapes of one fake — agree bit-for-bit,
        # the semantic-cache parity property), admission 429s once
        # queued texts would exceed ``max_queued_encode_texts``, and the
        # tpu:encode_* metric families render live values.
        self.max_queued_encode_texts = int(max_queued_encode_texts)
        self.encode_texts_total = 0
        self.encode_in_flight = 0  # tpu:encode_queue_depth mirror
        self.encode_batch_size_hist = Histogram(
            bounds=(1.0, 2.0, 4.0, 8.0, 16.0, 32.0)
        )
        self.encode_seconds_hist = Histogram(
            bounds=(0.005, 0.02, 0.05, 0.1, 0.25, 1.0, 4.0)
        )
        # -- multi-host slice-group emulation (FakeSliceGroup) -------------
        # This state becomes the LEADER (ordinal 0) of a simulated slice:
        # /health conjoins member liveness, a failed group refuses data-
        # plane connections (the fatal-exited leader as the router sees
        # it), and build_fake_follower_app() serves the follower
        # ordinals' probe/drain surface against the same group object.
        self.slice_group = slice_group

    def inject(self, kind: str, **params) -> None:
        """Arm a fault: ``refuse`` (close the connection pre-response;
        count=N or -1), ``error_5xx`` (status=503, count=N),
        ``reject_429`` (retry_after=1, count=N), ``stall_stream``
        (after_tokens=K: emit K chunks then hang until torn down),
        ``slow_admission`` (delay_s before the first byte)."""
        if kind not in (
            "refuse", "error_5xx", "reject_429", "stall_stream",
            "slow_admission",
        ):
            raise ValueError(f"unknown injection kind {kind!r}")
        params.setdefault("count", -1)
        self.injections[kind] = dict(params)

    def clear_injection(self, kind: str) -> None:
        self.injections.pop(kind, None)

    def _take_injection(self, kind: str):
        """Params if the fault is armed (consuming one count), else None."""
        inj = self.injections.get(kind)
        if inj is None or inj["count"] == 0:
            return None
        if inj["count"] > 0:
            inj["count"] -= 1
        return inj

    @property
    def in_flight(self) -> int:
        return self.num_running + self.num_waiting

    def token_interval(self) -> float:
        """Current per-token interval: degrades linearly once in-flight
        work oversubscribes capacity (the deterministic ITL model the
        overload tests measure)."""
        base = 1.0 / self.tokens_per_sec
        if self.capacity:
            return base * max(1.0, self.in_flight / self.capacity)
        return base

    def note_prompt(self, prompt_text: str) -> tuple:
        """Chunk-chain prefix-cache simulation.

        Walks the prompt's chained chunk digests against this engine's
        cached set: the matched leading run is a local hit; with
        ``remote_store_import``, a contiguous store-resident extension
        imports (counted as hit — the real prefetch plane lands imports
        in the prefix cache before schedule, so ``match_prefix`` serves
        them); the rest is cold prefill.  Returns
        ``(uncached_chars, imported_chars)`` for the TTFT cost model.
        """
        cc = self.prefix_chunk_chars
        chain = fake_prefix_chain(prompt_text, cc)
        matched = 0
        for digest in chain:
            if digest not in self._seen_chunks:
                break
            matched += 1
        imported = 0
        if self.remote_store_import:
            for digest in chain[matched:]:
                if digest not in self.shared_store:
                    break
                imported += 1
        total_chars = max(len(prompt_text), 1)
        hit_chars = min((matched + imported) * cc, total_chars)
        self.prefix_query_tokens += max(1, total_chars // 4)
        self.prefix_hit_tokens += hit_chars // 4
        self._seen_chunks.update(chain)
        if self.remote_store_import:
            self.shared_store.update(chain)  # px-export of computed chunks
        uncached_chars = max(0, total_chars - hit_chars)
        imported_chars = min(imported * cc, total_chars)
        return uncached_chars, imported_chars

    def prefill_seconds(self, uncached_chars: int, imported_chars: int) -> float:
        """TTFT beyond the base: cold-prefill the uncached tail, import
        the store-warmed span (cheaper), stretch with oversubscription
        when the load model is on.  0.0 with the cost model off."""
        if not self.prefill_chars_per_sec:
            return 0.0
        import_rate = (
            self.store_import_chars_per_sec or 4.0 * self.prefill_chars_per_sec
        )
        cost = (
            uncached_chars / self.prefill_chars_per_sec
            + imported_chars / import_rate
        )
        if self.prefill_scales_with_load and self.capacity:
            cost *= max(1.0, (self.in_flight + 1) / self.capacity)
        return cost

    @property
    def prefix_hit_rate(self) -> float:
        if not self.prefix_query_tokens:
            return 0.0
        return self.prefix_hit_tokens / self.prefix_query_tokens

    @property
    def prefix_cached_chunks(self) -> int:
        """Resident content chunks — the tpu:prefix_cache_blocks mirror."""
        return len(self._seen_chunks)

    @property
    def kv_usage(self) -> float:
        return min(1.0, self.num_running * 0.05)


def _sse(data: dict) -> bytes:
    return f"data: {json.dumps(data)}\n\n".encode()


def fake_prefix_chain(prompt_text: str, chunk_chars: int = 64) -> list:
    """Deterministic stand-in for the engine's prefix hash chain: one
    chained blake2b digest per ``chunk_chars`` of prompt text.  Prefill
    and decode fakes derive the SAME chain from the same prompt — the
    content-keyed-store property the real handoff relies on."""
    chain = []
    h = hashlib.blake2b(digest_size=8)
    for start in range(0, max(len(prompt_text), 1), chunk_chars):
        h.update(prompt_text[start : start + chunk_chars].encode("utf-8"))
        chain.append(h.hexdigest())
    return chain


def fake_embedding(text: str, dim: int = 32) -> list:
    """Deterministic unit vector for ``text`` — a function of the text
    ALONE (no per-engine seed), so every fake in a fleet returns the
    identical embedding for the same input.  That's the property the
    router's semantic cache tests lean on: a cached answer must be
    byte-identical to a fresh one regardless of which backend served it."""
    digest = hashlib.blake2b(text.encode("utf-8"), digest_size=32).digest()
    raw = [((b / 255.0) * 2.0 - 1.0) for b in digest[:dim]]
    norm = sum(v * v for v in raw) ** 0.5 or 1.0
    return [round(v / norm, 8) for v in raw]


def _word(rng: random.Random) -> str:
    return rng.choice(
        ["alpha", "bravo", "charlie", "delta", "echo", "foxtrot", "tensor", "tpu"]
    )


def build_fake_engine_app(state: FakeEngineState | None = None) -> web.Application:
    state = state or FakeEngineState()
    app = web.Application()
    app["state"] = state

    async def models(_request: web.Request) -> web.Response:
        state.total_model_probes += 1
        return web.json_response(
            {
                "object": "list",
                "data": [
                    {
                        "id": state.model,
                        "object": "model",
                        "created": int(time.time()),
                        "owned_by": "fake-tpu-engine",
                    }
                ],
            }
        )

    async def health(_request: web.Request) -> web.Response:
        if state.slice_group is not None:
            problem = state.slice_group.problem()
            if problem is not None:
                # The slice is ONE endpoint whose health is the
                # conjunction of its members (the real leader's
                # /health conjoins GroupLivenessMonitor.problem()).
                return web.json_response(
                    {"status": "unhealthy", "problem": problem,
                     "epoch": state.slice_group.epoch},
                    status=503,
                )
        return web.json_response({"status": "ok", "last_step_age_s": 0.0})

    async def ready(_request: web.Request) -> web.Response:
        if state.draining:
            return web.json_response(
                {"status": "draining", "in_flight_streams": state.num_running},
                status=503,
            )
        return web.json_response({"status": "ready"})

    async def drain_endpoint(_request: web.Request) -> web.Response:
        state.draining = True
        return web.json_response(
            {"draining": True, "in_flight_streams": state.num_running}
        )

    async def metrics(_request: web.Request) -> web.Response:
        # Same serializer + same names as the real engine server
        # (engine/server/api_server.py) so the observability contract is
        # identical against fake and real engines.
        text = _render_metrics_pairs(state)
        return web.Response(text=text)

    def _render_metrics_pairs(state: FakeEngineState) -> str:
        # With a capacity model, "waiting" is the oversubscription beyond
        # capacity (queue-depth gauge the overload tests assert on).
        waiting = (
            max(0, state.num_running - state.capacity)
            if state.capacity else state.num_waiting
        )
        return vocab.render_prometheus([
            (vocab.TPU_NUM_REQUESTS_RUNNING, state.num_running),
            (vocab.TPU_NUM_REQUESTS_WAITING, waiting),
            (vocab.TPU_HBM_KV_USAGE_PERC, state.kv_usage),
            (vocab.TPU_PREFIX_CACHE_HIT_RATE, state.prefix_hit_rate),
            # Prefix-cache truth (live values from the chunk-chain sim):
            # the router's fleet popularity view scrapes these, so the
            # whole reconcile/fleet-hit-rate path runs in CI on fakes.
            (vocab.TPU_PREFIX_CACHE_HIT_TOKENS, state.prefix_hit_tokens),
            (vocab.TPU_PREFIX_CACHE_QUERY_TOKENS, state.prefix_query_tokens),
            (vocab.TPU_PREFIX_CACHE_BLOCKS, state.prefix_cached_chunks),
            (vocab.TPU_HOST_KV_USAGE_PERC, 0.0),
            (vocab.TPU_DUTY_CYCLE, min(1.0, state.num_running * 0.1)),
            (vocab.TPU_TOTAL_PROMPT_TOKENS, state.total_prompt_tokens),
            (vocab.TPU_TOTAL_GENERATED_TOKENS, state.total_generated_tokens),
            (vocab.TPU_TOTAL_FINISHED_REQUESTS, state.total_finished),
            (vocab.TPU_NUM_PREEMPTIONS, 0),
            # Pipeline-health + capability gauges: the fake engine has no
            # device (zero host gap) and no adapters, but the families
            # must exist for the scrape contract (metric_registry.py —
            # stackcheck SC303 pins this mirror).
            (vocab.TPU_DECODE_HOST_GAP_MS, 0.0),
            (vocab.TPU_LOADED_LORAS, 0),
            # Cross-engine prefix sharing + speculative decoding counters
            # (no store and no drafter here; contract parity only).
            (vocab.TPU_REMOTE_PREFIX_BLOCKS_FETCHED, 0),
            (vocab.TPU_REMOTE_PREFIX_BLOCKS_EXPORTED, 0),
            # Disaggregated serving emulation (--disagg-role): primes
            # served and simulated handoff prefetch outcomes — live
            # values, so router CI can assert the whole two-phase flow
            # through /metrics alone.
            (vocab.TPU_DISAGG_PREFILL_PRIMES, state.disagg_prefill_primes),
            (vocab.TPU_DISAGG_HANDOFF_HITS, state.disagg_handoff_hits),
            (vocab.TPU_DISAGG_HANDOFF_MISSES, state.disagg_handoff_misses),
            (vocab.TPU_SPEC_TOKENS_DRAFTED, 0),
            (vocab.TPU_SPEC_TOKENS_ACCEPTED, 0),
            # Draft-model speculation: no device, so no draft forwards
            # ever run — zero, but the family must exist (SC303).
            (vocab.TPU_SPEC_DRAFT_FRACTION_SECONDS, 0.0),
            # The fake engine serves every prompt instantly, so no mixed
            # chunking ever happens (windowed or not) — but the counters
            # must exist so the scrape contract matches the real engine.
            (vocab.TPU_PREFILL_CHUNK_TOKENS, 0),
            (vocab.TPU_MIXED_WINDOW_CHUNK_TOKENS, 0),
            # Overlapped window dispatch: no device, so no transfers ever
            # overlap a window — zero, but the family must exist
            # (tpu:mixed_window_prompts_per_window renders below).
            (vocab.TPU_WINDOW_TRANSFER_OVERLAP_SECONDS, 0.0),
            # Async KV transfer plane: the fake engine has no remote
            # store, but the families must exist for the scrape contract
            # (obs.render_metrics below adds the matching
            # tpu:remote_kv_fetch/offload_stage histograms).
            (vocab.TPU_KV_PREFETCH_HIT, 0),
            (vocab.TPU_KV_PREFETCH_WASTE, 0),
            (vocab.TPU_KV_PREFETCH_INFLIGHT, 0),
            # Overload protection + watchdog families (scrape contract
            # parity with the real engine; the fake engine's "step loop"
            # is the event loop, so its age is always fresh).
            (vocab.TPU_ADMISSION_REJECTED, state.admission_rejected),
            (vocab.TPU_DEADLINE_EXPIRED, state.deadline_expired),
            (vocab.TPU_QUEUED_PROMPT_TOKENS, 0),
            (vocab.TPU_LAST_STEP_AGE, 0.0),
            # K-step decode windows: the fake engine has no device, so
            # nothing falls back and nothing is wasted — but both
            # families must exist for the scrape contract
            # (TPU_MULTISTEP_FALLBACK renders its labeled header below).
            (vocab.TPU_MULTISTEP_WASTED_TOKENS, 0),
            # Batched encode lane (embed/rerank/score): live values from
            # the fake lane below — texts encoded and the queue-depth
            # gauge — so router encode-lane CI asserts batching through
            # /metrics alone (SC303; the batch-size/latency histograms
            # render below).
            (vocab.TPU_ENCODE_TEXTS, state.encode_texts_total),
            (vocab.TPU_ENCODE_QUEUE_DEPTH, state.encode_in_flight),
        ]) + render_histogram(
            vocab.TPU_ENCODE_BATCH_SIZE, state.encode_batch_size_hist,
        ) + render_histogram(
            vocab.TPU_ENCODE_SECONDS, state.encode_seconds_hist,
        ) + vocab.render_labeled_counter(
            vocab.TPU_MULTISTEP_FALLBACK, "reason",
            dict.fromkeys(vocab.TPU_MULTISTEP_FALLBACK_REASONS, 0),
        ) + vocab.render_labeled_counter2(
            # Fused speculative windows: no device, so no drafts — but
            # the family (all outcome x drafter cells) must exist for
            # the scrape contract (SC303).
            vocab.TPU_SPEC_WINDOW_TOKENS, ("outcome", "drafter"),
            {
                (o, d): 0
                for o in vocab.TPU_SPEC_WINDOW_OUTCOMES
                for d in vocab.TPU_SPEC_WINDOW_DRAFTERS
            },
        ) + vocab.render_labeled_counter2(
            # Quantized KV tiering plane: no KV tiers in the fake, but
            # both families must exist for the scrape contract (SC303).
            vocab.TPU_KV_WIRE_BYTES, ("tier", "format"),
            {
                (t, f): 0
                for t in vocab.TPU_KV_WIRE_TIERS
                for f in vocab.TPU_KV_WIRE_FORMATS
            },
        ) + vocab.render_labeled_counter(
            vocab.TPU_KV_SNAPSHOT_FORMAT, "version",
            dict.fromkeys(vocab.TPU_KV_SNAPSHOT_VERSIONS, 0),
        ) + render_histogram(
            # Packed multi-prompt windows: the fake engine never packs
            # (no device scan), so the histogram is empty — but the
            # family must exist for the scrape contract (SC303).
            vocab.TPU_MIXED_WINDOW_PROMPTS,
            Histogram(bounds=(1.0, 2.0, 4.0, 8.0, 16.0, 32.0)),
        ) + vocab.render_prometheus([
            # Slice-group lifecycle: live values in slice mode so the
            # whole group-liveness contract (epoch steps on restart,
            # relays count) is scrapeable against fakes in CI; zeros —
            # but stable families — single-host (SC303).
            (vocab.TPU_LOCKSTEP_GROUP_EPOCH,
             state.slice_group.epoch if state.slice_group else 0),
            (vocab.TPU_SLICE_DRAIN_RELAYS,
             state.slice_group.drain_relays if state.slice_group else 0),
        ]) + vocab.render_labeled_gauge(
            vocab.TPU_LOCKSTEP_MEMBER_LAST_ACK, "member",
            {} if state.slice_group is None else {
                str(pid): age
                for pid, age in state.slice_group.member_ack_ages().items()
            },
        ) + vocab.render_labeled_counter(
            vocab.TPU_LOCKSTEP_MEMBER_FAILURES, "reason",
            {
                **dict.fromkeys(vocab.TPU_LOCKSTEP_FAILURE_REASONS, 0),
                **(
                    state.slice_group.member_failures
                    if state.slice_group else {}
                ),
            },
        ) + vocab.render_labeled_counter(
            # XLA compile events per executable key: live values when
            # simulate_compiles is on, empty header otherwise — family
            # present either way for the scrape contract (SC303).
            vocab.TPU_COMPILE_SECONDS, "executable",
            state.obs.compile_tracker.seconds_by_executable(),
        ) + vocab.render_prometheus([
            (vocab.TPU_COMPILED_SHAPES,
             state.obs.compile_tracker.compiled_shapes()),
            (vocab.TPU_OBS_TRACE_DROPPED, state.obs.tracer.dropped),
        ]) + state.obs.render_metrics()

    async def debug_requests(_request: web.Request) -> web.Response:
        return web.json_response(state.obs.debug_payload())

    async def debug_request(request: web.Request) -> web.Response:
        snap = state.obs.request_payload(request.match_info["request_id"])
        if snap is None:
            return web.json_response(
                {"error": {"message": "unknown request id"}}, status=404
            )
        return web.json_response(snap)

    async def debug_windows(request: web.Request) -> web.Response:
        return web.json_response(
            state.obs.windows_payload(seq=request.query.get("seq") or None)
        )

    async def debug_compiles(_request: web.Request) -> web.Response:
        # Mirror of the real engine's compiles_payload(), jax-free: the
        # fake has no config-derived shape inventory, so coverage reports
        # the observed families as fully covered (contract tests assert
        # the payload SHAPE; the coverage math is engine-side logic).
        tracker = state.obs.compile_tracker
        coverage = {}
        for key in tracker.seconds_by_executable():
            fam = key.split("[", 1)[0]
            ent = coverage.setdefault(fam, {"compiled": 0, "expected": 0})
            ent["compiled"] += 1
            ent["expected"] += 1
        return web.json_response({
            "enabled": state.obs.enabled,
            "compiled_shapes": tracker.compiled_shapes(),
            "compile_seconds": round(tracker.compile_seconds(), 6),
            "executables": tracker.snapshot(),
            "coverage": coverage,
        })

    async def chat_completions(request: web.Request) -> web.StreamResponse:
        return await _completion_common(request, chat=True)

    async def completions(request: web.Request) -> web.StreamResponse:
        return await _completion_common(request, chat=False)

    def _finish_trace(
        request_id: str, t_recv: float, t_first: float, t_end: float
    ) -> None:
        """Simulated request timeline, partitioned exactly like the real
        engine's span set: zero queue wait, prefill = TTFT sleep, decode =
        token emission, zero detokenize."""
        obs = state.obs
        if not obs.enabled:
            return
        obs.request_hists["queue_time"].observe(0.0)
        obs.request_hists["ttft"].observe(t_first - t_recv)
        obs.request_hists["prefill_time"].observe(t_first - t_recv)
        obs.request_hists["decode_time"].observe(t_end - t_first)
        obs.request_hists["e2e_latency"].observe(t_end - t_recv)
        obs.tracer.add_span(request_id, "engine.prefill", t_recv, t_first)
        obs.tracer.add_span(request_id, "engine.decode", t_first, t_end)
        obs.tracer.add_span(
            request_id, "engine.detokenize", t_end, t_end, accumulated=True
        )
        obs.tracer.finish(request_id, end=t_end)

    async def _completion_common(request: web.Request, chat: bool) -> web.StreamResponse:
        # -- fault injection + overload surface (docs/robustness.md) ------
        state.data_plane_hits += 1
        if state.draining:
            resp = web.json_response(
                {"error": {"message": "server is draining for shutdown",
                           "type": "shutting_down", "code": 503}},
                status=503,
            )
            resp.force_close()
            return resp
        inj = state._take_injection("refuse")
        if inj is not None:
            # Connect-stage failure as the router sees it: the transport
            # dies before any response byte (ServerDisconnectedError).
            if request.transport is not None:
                request.transport.close()
            raise ConnectionResetError("injected connection refusal")
        if (
            state.slice_group is not None
            and state.slice_group.problem() is not None
        ):
            # A failed slice's leader fatal-exits within the member
            # timeout: the router sees connection refusals (breaker
            # opens, retry budget fails the request over to healthy
            # backends) — never a clean 5xx from a half-dead group.
            if request.transport is not None:
                request.transport.close()
            raise ConnectionResetError("slice group failed (leader exited)")
        inj = state._take_injection("error_5xx")
        if inj is not None:
            return web.json_response(
                {"error": {"message": "injected backend failure",
                           "type": "internal_error"}},
                status=int(inj.get("status", 503)),
            )
        inj = state._take_injection("slow_admission")
        if inj is not None:
            await asyncio.sleep(float(inj.get("delay_s", 0.2)))
        body = await request.json()
        state.last_headers = dict(request.headers)
        stream = bool(body.get("stream", False))
        max_tokens = int(
            body.get("max_tokens")
            or body.get("max_completion_tokens")
            or state.max_tokens_default
        )
        # Deadline contract parity with the real engine server: an
        # already-expired propagated deadline is shed with a 504.
        deadline_hdr = request.headers.get("x-request-deadline")
        if deadline_hdr is not None:
            try:
                deadline = float(deadline_hdr)
            except (TypeError, ValueError):
                deadline = None
            if deadline is not None and time.time() >= deadline:
                state.deadline_expired += 1
                return web.json_response(
                    {"error": {"message": "request deadline already "
                               "expired at admission",
                               "type": "deadline_expired", "code": 504}},
                    status=504,
                )
        inj = state._take_injection("reject_429")
        retry_after = int(inj.get("retry_after", 1)) if inj is not None else None
        if retry_after is None and (
            state.admission_control
            and state.capacity
            and state.in_flight >= state.capacity + state.max_queued
        ):
            retry_after = max(1, state.in_flight // state.capacity)
        if retry_after is not None:
            state.admission_rejected += 1
            return web.json_response(
                {
                    "error": {
                        "message": "engine overloaded: "
                                   f"{state.in_flight} requests in flight",
                        "type": "overloaded",
                        "code": 429,
                        "detail": {
                            "queued_requests": max(
                                0,
                                state.in_flight - (state.capacity or 0),
                            ),
                            "max_queued_requests": state.max_queued,
                            "kv_usage_perc": state.kv_usage,
                        },
                    }
                },
                status=429,
                headers={"Retry-After": str(retry_after)},
            )
        stall_after = None
        inj = state._take_injection("stall_stream")
        if inj is not None:
            stall_after = int(inj.get("after_tokens", 1))
        if chat:
            prompt_text = json.dumps(body.get("messages", ""))
        else:
            prompt_text = str(body.get("prompt", ""))
        uncached_chars, imported_chars = state.note_prompt(prompt_text)
        # Honor the router-assigned request id + trace context (the real
        # engine does the same), so router and engine timelines join.
        request_id = (
            request.headers.get("x-request-id")
            or f"cmpl-{uuid.uuid4().hex[:16]}"
        )

        # -- disagg prefill prime (x-disagg-phase) -------------------------
        # Same contract as the real engine server: run the (simulated)
        # prefill, record the eager export, return the handoff token
        # with zero completion tokens.
        if request.headers.get("x-disagg-phase") == "prefill":
            state.total_requests += 1
            state.num_running += 1
            try:
                await asyncio.sleep(state.ttft)  # the prefill cost
                chain = fake_prefix_chain(prompt_text)
                exported = state.disagg_role in ("prefill", "both")
                if exported:
                    state.shared_store.update(chain)
                    state.exports.append(chain)
                state.disagg_prefill_primes += 1
                prompt_tokens = max(1, len(prompt_text) // 4)
                state.total_prompt_tokens += prompt_tokens
                return web.json_response(
                    {
                        "id": request_id,
                        "object": "disagg.prefill",
                        "created": int(time.time()),
                        "model": body.get("model", state.model),
                        "disagg": {"handoff": {
                            "chain": chain,
                            "chain_len": len(chain),
                            "chain_tail": chain[-1],
                            "prompt_tokens": prompt_tokens,
                            "block_size": 16,
                            "px": "px:fake:",
                            "exported": exported,
                        }},
                        "usage": {
                            "prompt_tokens": prompt_tokens,
                            "completion_tokens": 0,
                            "total_tokens": prompt_tokens,
                        },
                    },
                    headers={"X-Request-Id": request_id},
                )
            finally:
                state.num_running -= 1

        # -- disagg decode-phase handoff (x-disagg-handoff) ----------------
        # A hit means the prefix chain "imported": decode starts with no
        # prefill work, so the TTFT sleep is skipped.  Any other outcome
        # keeps the full TTFT (the in-place recompute fallback).
        disagg_outcome = None
        ttft_s = state.ttft + state.prefill_seconds(
            uncached_chars, imported_chars
        )
        handoff_hdr = request.headers.get("x-disagg-handoff")
        if handoff_hdr:
            try:
                handoff = json.loads(handoff_hdr)
            except json.JSONDecodeError:
                handoff = None
            if state.prefetch_outcome is not None:
                disagg_outcome = state.prefetch_outcome
            elif state.disagg_role not in ("decode", "both"):
                disagg_outcome = "disabled"
            elif (
                isinstance(handoff, dict)
                and handoff.get("exported")
                and handoff.get("chain_tail") in state.shared_store
            ):
                disagg_outcome = "hit"
            else:
                disagg_outcome = "miss"
            if disagg_outcome == "hit":
                state.disagg_handoff_hits += 1
                ttft_s = 0.0
            else:
                state.disagg_handoff_misses += 1
        t_recv = time.time()
        state.obs.start_request(
            request_id,
            parse_traceparent(request.headers.get("traceparent")),
            model=body.get("model", state.model), stream=stream,
        )
        state.obs.tracer.add_span(request_id, "engine.queue", t_recv, t_recv)
        created = int(t_recv)
        state.total_requests += 1
        state.num_running += 1
        state.total_prompt_tokens += max(1, len(prompt_text) // 4)
        # One simulated flight record per request: the whole decode rides
        # one "window" (k = token budget, one row), so /debug/windows and
        # the /debug/requests/{id} join are contract-testable without a
        # device.
        rec = state.obs.recorder.on_dispatch(
            "decode", k=max_tokens, rows=1, seq_ids=(request_id,),
        )
        if state.simulate_compiles and uncached_chars and state.obs.enabled:
            sig = f"chars{1 << max(0, uncached_chars - 1).bit_length()}"
            if (
                f"prefill_fn[{sig}]"
                not in state.obs.compile_tracker.seconds_by_executable()
            ):
                state.obs.compile_tracker.record("prefill_fn", sig, ttft_s)
                state.obs.on_compile(
                    (request_id,),
                    state.obs.compile_tracker.drain_events(),
                    rec,
                )
        try:
            object_name = "chat.completion.chunk" if chat else "text_completion"
            if stream:
                stream_headers = {
                    "Content-Type": "text/event-stream",
                    "Cache-Control": "no-cache",
                    "X-Request-Id": request_id,
                }
                if disagg_outcome is not None:
                    stream_headers["X-Disagg-Prefix"] = disagg_outcome
                response = web.StreamResponse(headers=stream_headers)
                # Prepare BEFORE the TTFT sleep, like the real engine
                # server: the router's backend_connect span must end at
                # connect, not absorb prefill time.
                await response.prepare(request)
                await asyncio.sleep(ttft_s)
                t_first = time.time()
                t_last = t_first
                for i in range(max_tokens):
                    token = _word(state._rng) + " "
                    if chat:
                        delta = {"content": token}
                        if i == 0:
                            delta["role"] = "assistant"
                        choice = {"index": 0, "delta": delta, "finish_reason": None}
                    else:
                        choice = {"index": 0, "text": token, "finish_reason": None}
                    chunk = {
                        "id": request_id,
                        "object": object_name,
                        "created": created,
                        "model": body.get("model", state.model),
                        "choices": [choice],
                    }
                    if i == 0 and state.obs.compile_tainted(request_id):
                        # Same first-chunk marker the real server stamps.
                        chunk["compile"] = True
                    await response.write(_sse(chunk))
                    state.total_generated_tokens += 1
                    if stall_after is not None and i + 1 >= stall_after:
                        # Injected stall: the stream hangs byte-less until
                        # the peer (router sock_read timeout, client
                        # disconnect) tears it down — the CancelledError
                        # lands in the abort tracking below.
                        await asyncio.Event().wait()
                    await asyncio.sleep(state.token_interval())
                    now = time.time()
                    if state.obs.enabled and i > 0:
                        state.obs.request_hists["itl"].observe(now - t_last)
                    t_last = now
                state.total_finished += 1
                state.obs.recorder.on_collect(
                    rec, tokens_emitted=max_tokens,
                    tokens_delivered=max_tokens,
                )
                _finish_trace(request_id, t_recv, t_first, time.time())
                final_choice = (
                    {"index": 0, "delta": {}, "finish_reason": "length"}
                    if chat
                    else {"index": 0, "text": "", "finish_reason": "length"}
                )
                await response.write(
                    _sse(
                        {
                            "id": request_id,
                            "object": object_name,
                            "created": created,
                            "model": body.get("model", state.model),
                            "choices": [final_choice],
                            "usage": {
                                "prompt_tokens": len(prompt_text) // 4,
                                "completion_tokens": max_tokens,
                                "total_tokens": len(prompt_text) // 4 + max_tokens,
                            },
                        }
                    )
                )
                await response.write(b"data: [DONE]\n\n")
                await response.write_eof()
                return response
            await asyncio.sleep(ttft_s)
            t_first = time.time()
            interval = state.token_interval()
            await asyncio.sleep(max_tokens * interval)
            text = " ".join(_word(state._rng) for _ in range(max_tokens))
            state.total_generated_tokens += max_tokens
            state.total_finished += 1
            state.obs.recorder.on_collect(
                rec, tokens_emitted=max_tokens, tokens_delivered=max_tokens,
            )
            if state.obs.enabled:
                # Same obs contract as the real engine: ITL is observed
                # per token gap regardless of stream mode.
                for _ in range(max(0, max_tokens - 1)):
                    state.obs.request_hists["itl"].observe(interval)
            _finish_trace(request_id, t_recv, t_first, time.time())
            if chat:
                choice = {
                    "index": 0,
                    "message": {"role": "assistant", "content": text},
                    "finish_reason": "length",
                }
                object_name = "chat.completion"
            else:
                choice = {"index": 0, "text": text, "finish_reason": "length"}
                object_name = "text_completion"
            resp_headers = {"X-Request-Id": request_id}
            if disagg_outcome is not None:
                resp_headers["X-Disagg-Prefix"] = disagg_outcome
            final_body = {
                "id": request_id,
                "object": object_name,
                "created": created,
                "model": body.get("model", state.model),
                "choices": [choice],
                "usage": {
                    "prompt_tokens": len(prompt_text) // 4,
                    "completion_tokens": max_tokens,
                    "total_tokens": len(prompt_text) // 4 + max_tokens,
                },
            }
            if state.obs.compile_tainted(request_id):
                # Same body marker the real server stamps non-streaming.
                final_body["compile"] = True
            return web.json_response(final_body, headers=resp_headers)
        except (asyncio.CancelledError, ConnectionResetError):
            # The peer tore the stream down (client disconnect, router
            # idle-read timeout, proxy teardown): record the abort so
            # propagation tests can assert the engine-side release
            # happened, then re-raise — cancellation must not be eaten.
            state.aborted_requests.append(request_id)
            if rec is not None and rec.collected_at is None:
                # Publish the flight record exactly once even on abort —
                # an uncollected record would leak from /debug/windows.
                state.obs.recorder.on_collect(rec)
            if state.obs.enabled:
                state.obs.on_abort(request_id)
            raise
        finally:
            state.num_running -= 1

    def _encode_gate(request: web.Request, texts: list):
        """PR-5-shaped overload protection for the fake encode lane:
        expired propagated deadline -> 504, queued texts past the cap ->
        structured 429 + Retry-After (same body shape as the real
        engine's encode admission).  Returns an error response or None."""
        deadline_hdr = request.headers.get("x-request-deadline")
        if deadline_hdr is not None:
            try:
                deadline = float(deadline_hdr)
            except (TypeError, ValueError):
                deadline = None
            if deadline is not None and time.time() >= deadline:
                state.deadline_expired += 1
                return web.json_response(
                    {"error": {"message": "request deadline already "
                               "expired at admission",
                               "type": "deadline_expired", "code": 504}},
                    status=504,
                )
        if (
            state.admission_control
            and state.encode_in_flight + len(texts)
            > state.max_queued_encode_texts
        ):
            state.admission_rejected += 1
            retry_after = max(1, state.encode_in_flight // 32)
            return web.json_response(
                {
                    "error": {
                        "message": (
                            "engine overloaded: "
                            f"{state.encode_in_flight} texts already "
                            "queued on the encode lane; retry after "
                            f"{retry_after}s"
                        ),
                        "type": "overloaded",
                        "code": 429,
                        "detail": {
                            "queued_requests": state.encode_in_flight,
                            "max_queued_requests":
                                state.max_queued_encode_texts,
                            "retry_after_s": retry_after,
                        },
                    }
                },
                status=429,
                headers={"Retry-After": str(retry_after)},
            )
        return None

    async def _encode_batch(texts: list) -> list:
        """One request = ONE simulated encode batch, like the real step
        thread's window-boundary drain: the whole list lands as a single
        forward, observed once in the batch-size histogram."""
        state.encode_in_flight += len(texts)
        t0 = time.time()
        try:
            await asyncio.sleep(state.ttft)
            return [fake_embedding(t) for t in texts]
        finally:
            state.encode_in_flight -= len(texts)
            state.encode_texts_total += len(texts)
            state.encode_batch_size_hist.observe(float(len(texts)))
            state.encode_seconds_hist.observe(time.time() - t0)

    async def embeddings(request: web.Request) -> web.Response:
        state.data_plane_hits += 1
        body = await request.json()
        state.last_headers = dict(request.headers)
        raw_input = body.get("input")
        inputs = [raw_input] if isinstance(raw_input, str) else raw_input
        if not isinstance(inputs, list) or not all(
            isinstance(x, str) for x in inputs
        ) or not inputs:
            return web.json_response(
                {"error": {"message": "'input' must be a string or list of "
                           "strings", "type": "invalid_request_error"}},
                status=400,
            )
        err = _encode_gate(request, inputs)
        if err is not None:
            return err
        state.total_requests += 1
        vectors = await _encode_batch(inputs)
        total_tokens = sum(max(1, len(t) // 4) for t in inputs)
        state.total_prompt_tokens += total_tokens
        return web.json_response({
            "object": "list",
            "data": [
                {"object": "embedding", "index": i, "embedding": vec}
                for i, vec in enumerate(vectors)
            ],
            "model": body.get("model", state.model),
            "usage": {"prompt_tokens": total_tokens,
                      "total_tokens": total_tokens},
        })

    async def rerank(request: web.Request) -> web.Response:
        state.data_plane_hits += 1
        body = await request.json()
        state.last_headers = dict(request.headers)
        query, documents = body.get("query"), body.get("documents")
        if not isinstance(query, str) or not isinstance(documents, list):
            return web.json_response(
                {"error": {"message": "'query' must be a string and "
                           "'documents' a list of strings",
                           "type": "invalid_request_error"}},
                status=400,
            )
        err = _encode_gate(request, [query] + documents)
        if err is not None:
            return err
        state.total_requests += 1
        vectors = await _encode_batch([query] + documents)
        qvec, dvecs = vectors[0], vectors[1:]
        results = [
            {"index": i, "document": {"text": documents[i]},
             "relevance_score": sum(a * b for a, b in zip(qvec, dvec))}
            for i, dvec in enumerate(dvecs)
        ]
        results.sort(key=lambda r: r["relevance_score"], reverse=True)
        top_n = body.get("top_n")
        if top_n is not None:
            results = results[:top_n]
        total_tokens = sum(
            max(1, len(t) // 4) for t in [query] + documents
        )
        return web.json_response({
            # Deterministic id (hash of the inputs, not a uuid) so a
            # cached rerank answer is byte-identical to a fresh one.
            "id": "rerank-" + hashlib.blake2b(
                json.dumps([query, documents], sort_keys=True).encode(),
                digest_size=8,
            ).hexdigest(),
            "model": body.get("model", state.model),
            "usage": {"prompt_tokens": total_tokens,
                      "total_tokens": total_tokens},
            "results": results,
        })

    async def score(request: web.Request) -> web.Response:
        state.data_plane_hits += 1
        body = await request.json()
        state.last_headers = dict(request.headers)

        def as_list(v):
            if isinstance(v, str):
                return [v]
            return v if isinstance(v, list) else None

        t1, t2 = as_list(body.get("text_1")), as_list(body.get("text_2"))
        if t1 is None or t2 is None or not t1 or not t2:
            return web.json_response(
                {"error": {"message": "'text_1' and 'text_2' must be "
                           "non-empty strings or lists of strings",
                           "type": "invalid_request_error"}},
                status=400,
            )
        if len(t1) == 1:
            t1 = t1 * len(t2)
        distinct = list(dict.fromkeys(t1 + t2))
        err = _encode_gate(request, distinct)
        if err is not None:
            return err
        state.total_requests += 1
        vectors = await _encode_batch(distinct)
        by_text = dict(zip(distinct, vectors))
        data = [
            {"object": "score", "index": i,
             "score": sum(x * y for x, y in zip(by_text[a], by_text[b]))}
            for i, (a, b) in enumerate(zip(t1, t2))
        ]
        total_tokens = sum(
            max(1, len(a) // 4) + max(1, len(b) // 4)
            for a, b in zip(t1, t2)
        )
        return web.json_response({
            "object": "list",
            "data": data,
            "model": body.get("model", state.model),
            "usage": {"prompt_tokens": total_tokens,
                      "total_tokens": total_tokens},
        })

    app.router.add_get("/v1/models", models)
    app.router.add_get("/health", health)
    app.router.add_get("/ready", ready)
    app.router.add_post("/drain", drain_endpoint)
    app.router.add_get("/metrics", metrics)
    app.router.add_get("/debug/requests", debug_requests)
    app.router.add_get("/debug/requests/{request_id}", debug_request)
    app.router.add_get("/debug/windows", debug_windows)
    app.router.add_get("/debug/compiles", debug_compiles)
    app.router.add_post("/v1/chat/completions", chat_completions)
    app.router.add_post("/v1/completions", completions)
    app.router.add_post("/v1/embeddings", embeddings)
    app.router.add_post("/v1/rerank", rerank)
    app.router.add_post("/rerank", rerank)
    app.router.add_post("/v1/score", score)
    app.router.add_post("/score", score)
    return app


def build_fake_follower_app(
    leader_state: FakeEngineState, ordinal: int
) -> web.Application:
    """Probe/drain surface of one follower ordinal in a fake slice group
    (the real follower serves exactly /health + /ready + POST /drain —
    api_server._run_follower).  POST /drain RELAYS to the leader: the
    whole slice drains through the leader's data plane, and the follower
    keeps "stepping" (stays healthy) until the group exits together."""
    group = leader_state.slice_group
    if group is None:
        raise ValueError("leader state has no slice_group")
    app = web.Application()

    async def health(_request: web.Request) -> web.Response:
        problem = group.problem()
        if problem is not None:
            return web.json_response(
                {"status": "unhealthy", "role": "follower",
                 "problem": problem},
                status=503,
            )
        return web.json_response(
            {"status": "ok", "role": "follower", "process_id": ordinal}
        )

    async def ready(_request: web.Request) -> web.Response:
        if group.drain_relayed:
            return web.json_response(
                {"status": "draining", "role": "follower"}, status=503
            )
        return web.json_response({"status": "ready", "role": "follower"})

    async def drain_endpoint(_request: web.Request) -> web.Response:
        group.relay_drain(ordinal)
        # The LEADER drains the group: it stops admitting and finishes
        # the in-flight streams; members exit together afterwards.
        leader_state.draining = True
        return web.json_response({
            "draining": True, "role": "follower", "relayed": True,
        })

    app.router.add_get("/health", health)
    app.router.add_get("/ready", ready)
    app.router.add_post("/drain", drain_endpoint)
    return app


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(description="Fake TPU serving engine")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=9000)
    parser.add_argument("--model", default="fake/llama-3-8b")
    parser.add_argument("--tokens-per-sec", type=float, default=500.0)
    parser.add_argument("--ttft", type=float, default=0.02)
    parser.add_argument(
        "--capacity", type=int, default=None,
        help="model max_num_seqs: per-token intervals degrade once "
        "in-flight exceeds this, the waiting gauge rises, and bounded "
        "admission 429s past capacity+max-queued (live-drive stand-in "
        "for a saturating engine; None keeps the constant-rate fake)",
    )
    parser.add_argument("--max-queued", type=int, default=0)
    parser.add_argument(
        "--disagg-role",
        default=None,
        choices=["prefill", "decode", "both", "encode"],
        help="emulate a disagg role pool member: prefill serves prime "
        "calls and records exports; decode honors handoff tokens with a "
        "simulated prefetch hit (TTFT skipped) or miss; encode marks a "
        "dedicated embed/rerank/score pool member",
    )
    args = parser.parse_args(argv)
    state = FakeEngineState(
        model=args.model, tokens_per_sec=args.tokens_per_sec, ttft=args.ttft,
        capacity=args.capacity, max_queued=args.max_queued,
        disagg_role=args.disagg_role,
    )
    web.run_app(
        build_fake_engine_app(state), host=args.host, port=args.port, access_log=None
    )


if __name__ == "__main__":
    main()
