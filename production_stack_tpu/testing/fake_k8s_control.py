"""Fake K8s control plane for operator tests (envtest analogue).

Serves just enough of the K8s REST API for the native StaticRoute operator
(native/operator/operator.cpp):

* ``GET /apis/production-stack.tpu.dev/v1alpha1/staticroutes`` — list, plus
  ``?watch=1`` chunked event stream.
* ``GET/POST/PUT /api/v1/namespaces/{ns}/configmaps[/{name}]``.
* ``PATCH .../staticroutes/{name}/status`` (merge-patch subresource).

Reference counterpart: the Go controller is tested with envtest (real API
server binaries, suite_test.go:32-61); those binaries don't exist here, so
this plays the same role — real HTTP semantics, in-memory state.

``projection_dir`` imitates the kubelet: every ConfigMap write also lands as
files under ``{projection_dir}/{ns}/{name}/{key}`` so a router started with
``--dynamic-config-json`` on that path sees updates the way a real pod sees
a projected ConfigMap.
"""

from __future__ import annotations

import asyncio
import copy
import json
import os
import uuid
from typing import Dict, List, Optional, Tuple

from aiohttp import web

GROUP = "production-stack.tpu.dev"
VERSION = "v1alpha1"
PLURAL = "staticroutes"


class FakeK8sControlPlane:
    def __init__(self, projection_dir: Optional[str] = None):
        self.staticroutes: Dict[Tuple[str, str], dict] = {}
        self.configmaps: Dict[Tuple[str, str], dict] = {}
        self.leases: Dict[Tuple[str, str], dict] = {}
        self.status_patches: List[dict] = []
        self.projection_dir = projection_dir
        self.watch_queues: List[asyncio.Queue] = []
        self._rv = 0
        # API load accounting (operator soak tests: the status-write /
        # watch-wake feedback loop must not hot-spin the API server).
        self.request_count = 0
        self.request_log: List[str] = []

    @web.middleware
    async def _count_requests(self, request: web.Request, handler):
        self.request_count += 1
        self.request_log.append(f"{request.method} {request.path}")
        return await handler(request)

    # -- state manipulation (the "kubectl" side) ---------------------------

    def next_rv(self) -> str:
        self._rv += 1
        return str(self._rv)

    async def create_staticroute(self, ns: str, name: str, spec: dict) -> dict:
        obj = {
            "apiVersion": f"{GROUP}/{VERSION}",
            "kind": "StaticRoute",
            "metadata": {
                "name": name,
                "namespace": ns,
                "uid": str(uuid.uuid4()),
                "generation": 1,
                "resourceVersion": self.next_rv(),
            },
            "spec": spec,
        }
        self.staticroutes[(ns, name)] = obj
        await self._emit("ADDED", obj)
        return obj

    async def update_staticroute_spec(self, ns: str, name: str, spec: dict) -> dict:
        obj = self.staticroutes[(ns, name)]
        obj["spec"] = spec
        obj["metadata"]["generation"] += 1
        obj["metadata"]["resourceVersion"] = self.next_rv()
        await self._emit("MODIFIED", obj)
        return obj

    async def delete_staticroute(self, ns: str, name: str) -> None:
        obj = self.staticroutes.pop((ns, name), None)
        if obj is not None:
            await self._emit("DELETED", obj)

    async def _emit(self, etype: str, obj: dict) -> None:
        for queue in list(self.watch_queues):
            await queue.put({"type": etype, "object": copy.deepcopy(obj)})

    async def wait_for_watcher(self, timeout: float = 5.0) -> None:
        deadline = asyncio.get_event_loop().time() + timeout
        while asyncio.get_event_loop().time() < deadline:
            if self.watch_queues:
                return
            await asyncio.sleep(0.02)
        raise TimeoutError("operator watch stream never connected")

    def get_status(self, ns: str, name: str) -> dict:
        return self.staticroutes[(ns, name)].get("status", {})

    def get_condition(self, ns: str, name: str, ctype: str) -> Optional[dict]:
        for cond in self.get_status(ns, name).get("conditions", []):
            if cond.get("type") == ctype:
                return cond
        return None

    # -- kubelet projection -------------------------------------------------

    def _project(self, ns: str, name: str, cm: dict) -> None:
        if not self.projection_dir:
            return
        target = os.path.join(self.projection_dir, ns, name)
        os.makedirs(target, exist_ok=True)
        for key, content in (cm.get("data") or {}).items():
            # Write-then-rename, like the kubelet's atomic symlink swap.
            tmp = os.path.join(target, f".{key}.tmp")
            with open(tmp, "w") as f:
                f.write(content)
            os.replace(tmp, os.path.join(target, key))

    # -- HTTP handlers ------------------------------------------------------

    def build_app(self) -> web.Application:
        app = web.Application(middlewares=[self._count_requests])
        app.router.add_get(
            f"/apis/{GROUP}/{VERSION}/{PLURAL}", self.handle_list_or_watch
        )
        # coordination.k8s.io Leases (operator leader election).
        app.router.add_get(
            "/apis/coordination.k8s.io/v1/namespaces/{ns}/leases/{name}",
            self.handle_lease_get,
        )
        app.router.add_post(
            "/apis/coordination.k8s.io/v1/namespaces/{ns}/leases",
            self.handle_lease_create,
        )
        app.router.add_put(
            "/apis/coordination.k8s.io/v1/namespaces/{ns}/leases/{name}",
            self.handle_lease_update,
        )
        app.router.add_get(
            f"/apis/{GROUP}/{VERSION}/namespaces/{{ns}}/{PLURAL}",
            self.handle_list_or_watch,
        )
        app.router.add_patch(
            f"/apis/{GROUP}/{VERSION}/namespaces/{{ns}}/{PLURAL}/{{name}}/status",
            self.handle_status_patch,
        )
        app.router.add_get(
            "/api/v1/namespaces/{ns}/configmaps/{name}", self.handle_cm_get
        )
        app.router.add_post(
            "/api/v1/namespaces/{ns}/configmaps", self.handle_cm_create
        )
        app.router.add_put(
            "/api/v1/namespaces/{ns}/configmaps/{name}", self.handle_cm_update
        )
        return app

    async def handle_list_or_watch(self, request: web.Request):
        ns = request.match_info.get("ns")
        items = [
            copy.deepcopy(obj)
            for (obj_ns, _), obj in sorted(self.staticroutes.items())
            if ns is None or obj_ns == ns
        ]
        if not request.query.get("watch"):
            return web.json_response(
                {
                    "apiVersion": f"{GROUP}/{VERSION}",
                    "kind": "StaticRouteList",
                    "metadata": {"resourceVersion": str(self._rv)},
                    "items": items,
                }
            )
        response = web.StreamResponse(
            status=200, headers={"Content-Type": "application/json"}
        )
        await response.prepare(request)
        queue: asyncio.Queue = asyncio.Queue()
        for obj in items:
            await queue.put({"type": "ADDED", "object": obj})
        self.watch_queues.append(queue)
        try:
            while True:
                event = await queue.get()
                await response.write(json.dumps(event).encode() + b"\n")
        except (ConnectionResetError, asyncio.CancelledError):
            pass
        finally:
            self.watch_queues.remove(queue)
        return response

    async def handle_status_patch(self, request: web.Request):
        ns, name = request.match_info["ns"], request.match_info["name"]
        obj = self.staticroutes.get((ns, name))
        if obj is None:
            return web.json_response({"reason": "NotFound"}, status=404)
        patch = await request.json()
        self.status_patches.append(
            {"namespace": ns, "name": name, "patch": copy.deepcopy(patch)}
        )
        # merge-patch semantics on the status subresource.
        status = obj.setdefault("status", {})
        for key, value in patch.get("status", {}).items():
            if value is None:
                status.pop(key, None)
            else:
                status[key] = value
        obj["metadata"]["resourceVersion"] = self.next_rv()
        # A real API server emits MODIFIED for status writes too — the
        # operator must not reconcile-loop on its own status patches.
        await self._emit("MODIFIED", obj)
        return web.json_response(obj)

    # -- coordination.k8s.io Leases (leader election) ----------------------

    async def handle_lease_get(self, request: web.Request):
        key = (request.match_info["ns"], request.match_info["name"])
        lease = self.leases.get(key)
        if lease is None:
            return web.json_response(
                {"kind": "Status", "reason": "NotFound", "code": 404},
                status=404,
            )
        return web.json_response(lease)

    async def handle_lease_create(self, request: web.Request):
        ns = request.match_info["ns"]
        lease = await request.json()
        name = lease.get("metadata", {}).get("name")
        if not name:
            return web.json_response({"reason": "Invalid"}, status=422)
        if (ns, name) in self.leases:
            return web.json_response({"reason": "AlreadyExists"}, status=409)
        lease.setdefault("metadata", {})["resourceVersion"] = self.next_rv()
        self.leases[(ns, name)] = lease
        return web.json_response(lease, status=201)

    async def handle_lease_update(self, request: web.Request):
        key = (request.match_info["ns"], request.match_info["name"])
        current = self.leases.get(key)
        if current is None:
            return web.json_response({"reason": "NotFound"}, status=404)
        lease = await request.json()
        sent_rv = lease.get("metadata", {}).get("resourceVersion")
        # Optimistic concurrency: two contenders racing an update must
        # conflict exactly like a real apiserver.
        if sent_rv != current["metadata"]["resourceVersion"]:
            return web.json_response(
                {"kind": "Status", "reason": "Conflict", "code": 409},
                status=409,
            )
        lease.setdefault("metadata", {})["resourceVersion"] = self.next_rv()
        self.leases[key] = lease
        return web.json_response(lease)

    async def handle_cm_get(self, request: web.Request):
        ns, name = request.match_info["ns"], request.match_info["name"]
        cm = self.configmaps.get((ns, name))
        if cm is None:
            return web.json_response(
                {"kind": "Status", "reason": "NotFound", "code": 404}, status=404
            )
        return web.json_response(cm)

    async def handle_cm_create(self, request: web.Request):
        ns = request.match_info["ns"]
        cm = await request.json()
        name = cm.get("metadata", {}).get("name")
        if not name:
            return web.json_response({"reason": "Invalid"}, status=422)
        if (ns, name) in self.configmaps:
            return web.json_response({"reason": "AlreadyExists"}, status=409)
        cm.setdefault("metadata", {})["resourceVersion"] = self.next_rv()
        self.configmaps[(ns, name)] = cm
        self._project(ns, name, cm)
        return web.json_response(cm, status=201)

    async def handle_cm_update(self, request: web.Request):
        ns, name = request.match_info["ns"], request.match_info["name"]
        if (ns, name) not in self.configmaps:
            return web.json_response({"reason": "NotFound"}, status=404)
        cm = await request.json()
        cm.setdefault("metadata", {})["resourceVersion"] = self.next_rv()
        self.configmaps[(ns, name)] = cm
        self._project(ns, name, cm)
        return web.json_response(cm)
