"""Minimal Helm-template renderer (Go text/template subset + sprig bits).

There is no ``helm`` binary in the CI/TPU images, but "the chart renders
clean" must still be testable (reference CI lints + template-renders the
chart on every PR, .github/workflows/functionality-helm-chart.yml:25-50).
This renderer implements exactly the template dialect used by
``helm/templates/*.yaml`` in this repo:

  actions        {{ expr }} with {{- / -}} whitespace trimming
  pipelines      value | fn arg | fn
  data access    .Values.a.b, $m.field, $.Release.Name, quoted strings, ints
  control flow   if / else / end, range $var := expr
  variables      {{ $x := expr }} assignment, {{ /* comments */ }}
  functions      default, quote, toYaml, nindent, indent, required,
                 eq, ne, not, and, or, kindIs, hasKey, gt, int, printf

It is NOT a general Helm implementation — unsupported constructs raise so
the chart cannot silently drift outside the tested subset.  Also usable as
a clusterless ``helm template`` stand-in:

  python -m production_stack_tpu.testing.helm_render helm \
      [-f overrides.yaml] [--set-name release]
"""

from __future__ import annotations

import json
import re
from typing import Any, Dict, List, Optional, Tuple

__all__ = ["render_chart", "render_template", "HelmTemplateError"]


class HelmTemplateError(Exception):
    pass


_TOKEN_RE = re.compile(r"\{\{(-?)\s*(.*?)\s*(-?)\}\}", re.S)


def _tokenize(source: str):
    """Yield ('text', str) and ('action', body, trim_left, trim_right)."""
    pos = 0
    for m in _TOKEN_RE.finditer(source):
        if m.start() > pos:
            yield ("text", source[pos : m.start()])
        yield ("action", m.group(2), m.group(1) == "-", m.group(3) == "-")
        pos = m.end()
    if pos < len(source):
        yield ("text", source[pos:])


# -- expression parsing ----------------------------------------------------

_WORD_RE = re.compile(
    r"""
      "(?:[^"\\]|\\.)*"      # double-quoted string
    | `[^`]*`                # raw string
    | \(|\)
    | \|
    | [^\s()|]+
    """,
    re.X,
)


def _lex_expr(expr: str) -> List[str]:
    return _WORD_RE.findall(expr)


class _Parser:
    """Pratt-less recursive parser for the tiny pipeline grammar."""

    def __init__(self, tokens: List[str]):
        self.tokens = tokens
        self.i = 0

    def peek(self) -> Optional[str]:
        return self.tokens[self.i] if self.i < len(self.tokens) else None

    def next(self) -> str:
        tok = self.tokens[self.i]
        self.i += 1
        return tok

    def parse_pipeline(self):
        """pipeline := command ('|' command)*  — returns nested call AST."""
        node = self.parse_command()
        while self.peek() == "|":
            self.next()
            fn = self.parse_command()
            # value | fn a b  ==  fn a b value
            if fn[0] != "call":
                fn = ("call", fn, [])
            node = ("call", fn[1], fn[2] + [node])
        return node

    def parse_command(self):
        """command := term term*  (first term is the function if >1)."""
        terms = [self.parse_term()]
        while self.peek() not in (None, "|", ")"):
            terms.append(self.parse_term())
        if len(terms) == 1:
            return terms[0]
        return ("call", terms[0], terms[1:])

    def parse_term(self):
        tok = self.next()
        if tok == "(":
            node = self.parse_pipeline()
            if self.next() != ")":
                raise HelmTemplateError("expected ')'")
            return node
        if tok.startswith('"'):
            return ("lit", json.loads(tok))
        if tok.startswith("`"):
            return ("lit", tok[1:-1])
        if re.fullmatch(r"-?\d+", tok):
            return ("lit", int(tok))
        if re.fullmatch(r"-?\d+\.\d+", tok):
            return ("lit", float(tok))
        if tok in ("true", "false"):
            return ("lit", tok == "true")
        if tok in ("nil", "null"):
            return ("lit", None)
        if tok.startswith("$") or tok.startswith("."):
            return ("path", tok)
        return ("name", tok)


def _parse_expr(expr: str):
    parser = _Parser(_lex_expr(expr))
    node = parser.parse_pipeline()
    if parser.peek() is not None:
        raise HelmTemplateError(f"trailing tokens in expression: {expr!r}")
    return node


# -- evaluation ------------------------------------------------------------


def _to_yaml(value: Any, indent: int = 0) -> str:
    """Subset YAML emitter (block style, deterministic order) matching what
    the chart needs from sprig's toYaml."""
    pad = " " * indent
    if isinstance(value, dict):
        if not value:
            return pad + "{}"
        lines = []
        for key, v in value.items():
            if isinstance(v, (dict, list)) and v:
                lines.append(f"{pad}{key}:")
                lines.append(_to_yaml(v, indent + 2))
            else:
                lines.append(f"{pad}{key}: {_scalar(v)}")
        return "\n".join(lines)
    if isinstance(value, list):
        if not value:
            return pad + "[]"
        lines = []
        for v in value:
            if isinstance(v, (dict, list)) and v:
                sub = _to_yaml(v, indent + 2)
                # fold the first key onto the dash line
                first, _, rest = sub.partition("\n")
                lines.append(f"{pad}- {first.strip()}")
                if rest:
                    lines.append(rest)
            else:
                lines.append(f"{pad}- {_scalar(v)}")
        return "\n".join(lines)
    return pad + _scalar(value)


_AMBIGUOUS_SCALAR_RE = re.compile(
    # Strings that YAML would re-type as bool/null/number must stay quoted
    # (sprig's toYaml quotes these; "2" as a label value must not become 2).
    r"^(true|false|yes|no|on|off|null|~|"
    r"[-+]?\d+|[-+]?\d*\.\d+([eE][-+]?\d+)?|0x[0-9a-fA-F]+)$",
    re.I,
)


def _scalar(v: Any) -> str:
    if v is None:
        return "null"
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, (int, float)):
        return str(v)
    s = str(v)
    if (
        s == ""
        or re.search(r"[:#{}\[\],&*?|>'\"%@`]", s)
        or s != s.strip()
        or _AMBIGUOUS_SCALAR_RE.match(s)
    ):
        return json.dumps(s)
    return s


def _truthy(v: Any) -> bool:
    return bool(v) and v != 0


class _Env:
    def __init__(self, root: Dict[str, Any]):
        self.root = root
        self.vars: Dict[str, Any] = {"$": root}
        self.dot: Any = root

    def child(self) -> "_Env":
        env = _Env(self.root)
        env.vars = dict(self.vars)
        env.dot = self.dot
        return env

    def lookup_path(self, path: str) -> Any:
        if path.startswith("$"):
            name, _, rest = path.partition(".")
            base = self.vars.get(name)
            if name not in self.vars:
                raise HelmTemplateError(f"undefined variable {name}")
            return _walk(base, rest)
        if path == ".":
            return self.dot
        return _walk(self.dot, path[1:])


def _walk(obj: Any, dotted: str) -> Any:
    if not dotted:
        return obj
    for part in dotted.split("."):
        if obj is None:
            return None
        if isinstance(obj, dict):
            obj = obj.get(part)
        else:
            raise HelmTemplateError(
                f"cannot access field {part!r} on {type(obj).__name__}"
            )
    return obj


def _eval(node, env: _Env) -> Any:
    kind = node[0]
    if kind == "lit":
        return node[1]
    if kind == "path":
        return env.lookup_path(node[1])
    if kind == "name":
        # bare function with no args, e.g. part of a pipeline
        return _call(node[1], [], env)
    if kind == "call":
        fn = node[1]
        if fn[0] == "name":
            args = [_eval(a, env) for a in node[2]]
            return _call(fn[1], args, env)
        if not node[2]:
            return _eval(fn, env)
        raise HelmTemplateError(f"cannot call non-function {fn!r}")
    raise HelmTemplateError(f"bad AST node {node!r}")


def _call(name: str, args: List[Any], env: _Env) -> Any:
    if name == "default":
        return args[1] if len(args) > 1 and _truthy(args[1]) else args[0]
    if name == "quote":
        v = args[0]
        if isinstance(v, bool):
            return '"true"' if v else '"false"'
        return json.dumps("" if v is None else str(v))
    if name == "toYaml":
        return _to_yaml(args[0])
    if name == "indent":
        n, text = args[0], str(args[1])
        pad = " " * int(n)
        return "\n".join(pad + line for line in text.splitlines())
    if name == "nindent":
        n, text = args[0], str(args[1])
        return "\n" + _call("indent", [n, text], env)
    if name == "required":
        msg, v = args[0], args[1]
        if v is None or v == "":
            raise HelmTemplateError(f"required value missing: {msg}")
        return v
    if name == "eq":
        return args[0] == args[1]
    if name == "ne":
        return args[0] != args[1]
    if name == "not":
        return not _truthy(args[0])
    if name == "and":
        result = True
        for a in args:
            result = a
            if not _truthy(a):
                return a
        return result
    if name == "or":
        for a in args:
            if _truthy(a):
                return a
        return args[-1] if args else None
    if name == "kindIs":
        kind, v = args[0], args[1]
        kinds = {
            "string": str, "map": dict, "slice": list,
            "bool": bool, "int": int, "float64": float,
        }
        if kind not in kinds:
            # Fail loud: a typo like kindIs "str" must not silently match.
            raise HelmTemplateError(f"unsupported kindIs kind {kind!r}")
        if kind == "int" and isinstance(v, bool):
            return False
        return isinstance(v, kinds[kind])
    if name == "hasKey":
        return isinstance(args[0], dict) and args[1] in args[0]
    if name == "print":
        return "".join(str(a) for a in args)
    if name == "gt":
        return args[0] > args[1]
    if name == "lt":
        return args[0] < args[1]
    if name == "hasPrefix":
        # sprig argument order: (hasPrefix PREFIX STRING).
        return str(args[1] or "").startswith(str(args[0] or ""))
    if name == "int":
        v = args[0]
        return int(v) if v not in (None, "") else 0
    if name == "printf":
        fmt, rest = args[0], args[1:]
        # Go verbs used in-chart: %s and %d behave like Python's.
        return fmt % tuple(rest)
    raise HelmTemplateError(f"unsupported template function {name!r}")


# -- block structure -------------------------------------------------------


def _parse_blocks(tokens: List[tuple]) -> List[tuple]:
    """Group the flat token stream into a tree of text/action/if/range."""
    def parse(i: int, terminators) -> Tuple[List[tuple], int, Optional[str]]:
        nodes: List[tuple] = []
        while i < len(tokens):
            tok = tokens[i]
            if tok[0] == "text":
                nodes.append(tok)
                i += 1
                continue
            body = tok[1]
            word = body.split(None, 1)[0] if body.strip() else ""
            if word in terminators:
                return nodes, i, word
            if word == "if":
                cond = body[2:].strip()
                then, i, term = parse(i + 1, {"else", "end"})
                otherwise: List[tuple] = []
                if term == "else":
                    else_body = tokens[i][1].split(None, 1)
                    if len(else_body) > 1 and else_body[1].startswith("if"):
                        # else if -> nested if inside the else branch
                        nested_cond = else_body[1][2:].strip()
                        inner, i, term2 = parse(i + 1, {"else", "end"})
                        sub_else: List[tuple] = []
                        if term2 == "else":
                            sub_else, i, _ = parse(i + 1, {"end"})
                        otherwise = [("if", nested_cond, inner, sub_else,
                                      tok[2], tok[3])]
                    else:
                        otherwise, i, _ = parse(i + 1, {"end"})
                nodes.append(("if", cond, then, otherwise, tok[2], tok[3]))
                i += 1
                continue
            if word == "range":
                spec = body[5:].strip()
                inner, i, _ = parse(i + 1, {"end"})
                nodes.append(("range", spec, inner, tok[2], tok[3]))
                i += 1
                continue
            nodes.append(("action", body, tok[2], tok[3]))
            i += 1
        return nodes, i, None

    nodes, i, _ = parse(0, set())
    if i != len(tokens):
        raise HelmTemplateError("unbalanced if/range/end")
    return nodes


def _exec_nodes(nodes: List[tuple], env: _Env, out: List[str]) -> None:
    for node in nodes:
        if node[0] == "text":
            out.append(node[1])
        elif node[0] == "action":
            body = node[1]
            if body.startswith("/*"):  # template comment
                continue
            m = re.match(r"(\$\w+)\s*:=\s*(.+)", body, re.S)
            if m:  # variable assignment: binds in the enclosing scope
                env.vars[m.group(1)] = _eval(_parse_expr(m.group(2)), env)
                continue
            value = _eval(_parse_expr(body), env)
            out.append("" if value is None else str(value))
        elif node[0] == "if":
            _, cond, then, otherwise, _, _ = node
            branch = then if _truthy(_eval(_parse_expr(cond), env)) else otherwise
            _exec_nodes(branch, env, out)
        elif node[0] == "range":
            _, spec, inner, _, _ = node
            m = re.match(r"(\$\w+)\s*:=\s*(.+)", spec)
            if not m:
                raise HelmTemplateError(
                    f"only 'range $var := expr' is supported, got {spec!r}"
                )
            var, expr = m.group(1), m.group(2)
            seq = _eval(_parse_expr(expr), env) or []
            for item in seq:
                child = env.child()
                child.vars[var] = item
                child.dot = item
                _exec_nodes(inner, child, out)
        else:
            raise HelmTemplateError(f"bad block node {node[0]}")


def _apply_trim(tokens: List[tuple]) -> List[tuple]:
    """Apply {{- and -}} whitespace trimming to adjacent text tokens."""
    out = list(tokens)
    for idx, tok in enumerate(out):
        if tok[0] != "action":
            continue
        _, body, tl, tr = tok
        if tl and idx > 0 and out[idx - 1][0] == "text":
            out[idx - 1] = ("text", out[idx - 1][1].rstrip(" \t").rstrip("\n"))
        if tr and idx + 1 < len(out) and out[idx + 1][0] == "text":
            out[idx + 1] = ("text", out[idx + 1][1].lstrip(" \t\n"))
    return out


def render_template(source: str, context: Dict[str, Any]) -> str:
    tokens = _apply_trim(list(_tokenize(source)))
    nodes = _parse_blocks(tokens)
    env = _Env(context)
    out: List[str] = []
    _exec_nodes(nodes, env, out)
    return "".join(out)


# -- chart-level API -------------------------------------------------------


def _deep_merge(base: Any, override: Any) -> Any:
    if isinstance(base, dict) and isinstance(override, dict):
        merged = dict(base)
        for key, value in override.items():
            merged[key] = _deep_merge(base.get(key), value)
        return merged
    return override


def render_chart(
    chart_dir: str,
    overrides: Optional[Dict[str, Any]] = None,
    release_name: str = "release",
    namespace: str = "default",
) -> Dict[str, str]:
    """Render every template; returns {template filename: rendered text}."""
    import os

    import yaml

    with open(os.path.join(chart_dir, "values.yaml")) as f:
        values = yaml.safe_load(f) or {}
    if overrides:
        values = _deep_merge(values, overrides)
    with open(os.path.join(chart_dir, "Chart.yaml")) as f:
        chart_meta = yaml.safe_load(f)
    context = {
        "Values": values,
        "Release": {"Name": release_name, "Namespace": namespace,
                    "Service": "Helm"},
        "Chart": chart_meta,
    }
    rendered = {}
    tpl_dir = os.path.join(chart_dir, "templates")
    for name in sorted(os.listdir(tpl_dir)):
        if not name.endswith((".yaml", ".yml")):
            continue
        with open(os.path.join(tpl_dir, name)) as f:
            rendered[name] = render_template(f.read(), context)
    return rendered


def main(argv=None) -> None:
    import argparse

    import yaml

    parser = argparse.ArgumentParser(
        description="Clusterless `helm template` stand-in"
    )
    parser.add_argument("chart_dir")
    parser.add_argument("-f", "--values", action="append", default=[])
    parser.add_argument("--set-name", default="release")
    parser.add_argument("--namespace", default="default")
    args = parser.parse_args(argv)

    overrides: Dict[str, Any] = {}
    for path in args.values:
        with open(path) as f:
            overrides = _deep_merge(overrides, yaml.safe_load(f) or {})
    rendered = render_chart(
        args.chart_dir, overrides, args.set_name, args.namespace
    )
    for name, text in rendered.items():
        print(f"---\n# Source: {name}")
        print(text)


if __name__ == "__main__":
    main()
