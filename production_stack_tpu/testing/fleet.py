"""In-process fleet harness: 20+ fake engines behind a real router,
seeded diurnal traffic replay, scale-through-drain, fault injection.

The CI-scale proof rig for ROADMAP item 2 (fleet-level admission +
SLO autoscaling; SURVEY §2.6's "10 QPS x 32 workers" CI smoke, scaled
up): everything runs on one asyncio loop — N :class:`FakeEngineState`
backends on aiohttp TestServers, the REAL router app (capacity model,
fleet admission, breaker, stats plane all live) proxying to them, and a
seeded Poisson arrival process whose rate follows a diurnal curve that
swings ``peak_qps/base_qps`` (10x in the acceptance test).  No TPU, no
sockets beyond loopback, no sleeps beyond the replay clock.

What it measures (per request, classified at response time):

* ``completed``   — 200 and the stream ran to ``[DONE]`` (goodput)
* ``shed_router`` — 429 with error type ``fleet_overloaded`` (the
  capacity model shed at the router; docs/robustness.md)
* ``shed_engine`` — 429 with any other error type (the engine's own
  bounded admission tripped — in a healthy fleet these are strictly
  RARER than and PRECEDED by router sheds)
* ``error``       — 5xx / connect failure before any stream byte
* ``dropped``     — the stream STARTED and then died before ``[DONE]``
  (the one class the scale-through-drain guarantee forbids entirely)

Scale events run mid-replay: ``scale_to(n)`` adds replicas to discovery
(instant, like pods passing readiness); scale-down goes THROUGH THE
DRAIN PATH — endpoints leave discovery first (no new routing picks),
then ``POST /drain`` flips the backend to rejecting new work, and the
harness waits for its in-flight streams to finish before calling the
replica gone (the k8s preStop ordering PR 5 wired into helm).

Fault injection rides :meth:`FakeEngineState.inject`: ``kill`` (connect
refusal), ``stall`` (stream hangs mid-token), ``flap_429`` (a 429 storm
from one backend), all revertible mid-replay.

Determinism: arrivals, prompts and injection schedules derive from one
``random.Random(seed)``; wall-clock enters only through the replay
clock itself, so aggregate assertions (goodput ratio, shed ordering,
zero drops) are stable in CI.
"""

from __future__ import annotations

import asyncio
import dataclasses
import json
import math
import random
import time
from typing import Callable, Dict, List, Optional, Tuple

from aiohttp.test_utils import TestClient, TestServer

from production_stack_tpu.router.app import build_app
from production_stack_tpu.router.parser import parse_args
from production_stack_tpu.router.service_discovery import (
    DISCOVERY_SERVICE,
    EndpointInfo,
    StaticServiceDiscovery,
)
from production_stack_tpu.router.services.request_service.request import (
    ENGINE_STATS_SCRAPER,
)
from production_stack_tpu.testing.fake_engine import (
    FakeEngineState,
    FakeSliceGroup,
    build_fake_engine_app,
    build_fake_follower_app,
)

MODEL = "fleet/fake-llama"


class MutableServiceDiscovery(StaticServiceDiscovery):
    """Static discovery whose endpoint set changes at runtime — the
    harness's stand-in for pods joining/leaving a k8s Service as the
    autoscaler acts."""

    def add(self, url: str, models: List[str]) -> None:
        if any(ep.url == url for ep in self._endpoints):
            return
        self._endpoints.append(EndpointInfo(url=url, model_names=list(models)))

    def remove(self, url: str) -> None:
        self._endpoints = [ep for ep in self._endpoints if ep.url != url]


@dataclasses.dataclass
class Outcome:
    """One replayed request's fate (timestamps on the replay clock)."""

    arrived_t: float
    done_t: float
    kind: str            # completed | shed_router | shed_engine | error | dropped
    status: int = 0
    chunks: int = 0
    itl_p95: float = 0.0  # per-request p95 token gap (completed only)
    phase: str = "replay"  # warmup | replay


@dataclasses.dataclass
class FleetBackend:
    index: int
    state: FakeEngineState
    server: TestServer
    url: str = ""
    active: bool = False


class FleetHarness:
    """N fake engines + the real router, driven by a seeded replay."""

    def __init__(
        self,
        num_engines: int = 20,
        *,
        seed: int = 0,
        capacity: int = 2,
        max_queued: int = 8,
        tokens_per_sec: float = 60.0,
        ttft: float = 0.01,
        max_tokens: int = 6,
        router_args: Tuple[str, ...] = (),
        fleet_admission: bool = True,
        default_slots: float = 8.0,
        routing_logic: str = "least_loaded",
        engine_kwargs: Optional[Dict] = None,
        base_port: Optional[int] = None,
        slice_members: int = 0,
        slice_member_timeout_s: float = 0.5,
    ):
        self.num_engines = int(num_engines)
        self.seed = int(seed)
        self.capacity = int(capacity)
        self.max_queued = int(max_queued)
        self.tokens_per_sec = float(tokens_per_sec)
        self.ttft = float(ttft)
        self.max_tokens = int(max_tokens)
        self.router_args = tuple(router_args)
        self.fleet_admission = bool(fleet_admission)
        self.default_slots = float(default_slots)
        self.routing_logic = routing_logic
        # Extra FakeEngineState kwargs (e.g. the prefix-cache/prefill
        # cost model the multi-round workload turns on) applied to every
        # backend at start().
        self.engine_kwargs = dict(engine_kwargs or {})
        # Fixed backend ports (base_port + index) instead of ephemeral
        # ones: consistent-hash placement (SessionRouter) hashes backend
        # URLs, so random ports make hash placement — and therefore every
        # seeded A/B against it — nondeterministic across runs.
        self.base_port = base_port
        # Multi-host slice emulation: with slice_members >= 2, backend 0
        # becomes the LEADER of a fake slice group — ONE discovery
        # endpoint whose health is the conjunction of its members — and
        # the follower ordinals get health-only endpoints OUTSIDE
        # discovery (k8s only exposes the ordinal-0 client service).
        self.slice_members = int(slice_members)
        self.slice_member_timeout_s = float(slice_member_timeout_s)
        self.slice_group: Optional[FakeSliceGroup] = None
        self.slice_follower_servers: List[TestServer] = []
        self.rng = random.Random(self.seed)
        self.backends: List[FleetBackend] = []
        self.outcomes: List[Outcome] = []
        # (replay_t, active_count) steps — the oracle's capacity timeline.
        self.active_timeline: List[Tuple[float, int]] = []
        # (replay_t, engine_index, armed) — fault windows; an engine with
        # an armed capacity-destroying fault contributes zero capacity to
        # the oracle (an omniscient admission schedule cannot serve work
        # on a killed/stalled/429-flapping replica either).
        self.fault_timeline: List[Tuple[float, int, bool]] = []
        self._discovery: Optional[MutableServiceDiscovery] = None
        self._client: Optional[TestClient] = None
        self._router_server: Optional[TestServer] = None
        self._app = None
        self._t0: float = 0.0
        # Strong refs to fire-and-forget event tasks (an unreferenced
        # ensure_future can be GC'd or destroyed pending at loop close);
        # wait_background() drains them before report()/close().
        self._background: List[asyncio.Task] = []

    # -- lifecycle ---------------------------------------------------------

    async def start(self, active: int = 2) -> None:
        if self.slice_members >= 2:
            self.slice_group = FakeSliceGroup(
                num_members=self.slice_members,
                member_timeout_s=self.slice_member_timeout_s,
            )
        for i in range(self.num_engines):
            state = FakeEngineState(
                model=MODEL,
                tokens_per_sec=self.tokens_per_sec,
                ttft=self.ttft,
                seed=self.seed + i,
                capacity=self.capacity,
                max_queued=self.max_queued,
                slice_group=self.slice_group if i == 0 else None,
                **self.engine_kwargs,
            )
            if self.base_port is not None:
                server = TestServer(
                    build_fake_engine_app(state), port=self.base_port + i
                )
            else:
                server = TestServer(build_fake_engine_app(state))
            await server.start_server()
            be = FleetBackend(index=i, state=state, server=server)
            be.url = str(server.make_url("")).rstrip("/")
            self.backends.append(be)

        if self.slice_group is not None:
            # Follower probe endpoints (ordinals 1..n-1): live servers so
            # probe/drain paths are real HTTP, but never in discovery —
            # the slice is ONE endpoint fronted by its leader.
            leader_state = self.backends[0].state
            for ordinal in range(1, self.slice_members):
                fsrv = TestServer(
                    build_fake_follower_app(leader_state, ordinal)
                )
                await fsrv.start_server()
                self.slice_follower_servers.append(fsrv)

        initial = self.backends[:active]
        for be in initial:
            be.active = True
        argv = [
            "--static-backends", ",".join(be.url for be in initial),
            "--static-models", ",".join(MODEL for _ in initial),
            "--routing-logic", self.routing_logic,
            "--engine-stats-interval", "0.25",
            "--request-stats-window", "3",
            "--fleet-default-slots", str(self.default_slots),
            *(() if self.fleet_admission else ("--no-fleet-admission",)),
            *self.router_args,
        ]
        args = parse_args(argv)
        self._app = build_app(args)
        # Swap in the mutable discovery (same object model the dynamic
        # config watcher uses) so scale events are a list mutation, and
        # re-point the scraper at it.
        registry = self._app["registry"]
        discovery = MutableServiceDiscovery(
            [be.url for be in initial], [[MODEL] for _ in initial]
        )
        registry.replace(DISCOVERY_SERVICE, lambda: discovery)
        registry.get(ENGINE_STATS_SCRAPER).service_discovery = discovery
        self._discovery = discovery
        self._router_server = TestServer(self._app)
        await self._router_server.start_server()
        self._client = TestClient(self._router_server)
        self._t0 = time.monotonic()
        self.active_timeline.append((0.0, active))

    async def close(self) -> None:
        # Drain outstanding background scale tasks BEFORE tearing the
        # backends down — an exception path that skipped
        # wait_background() must not close servers out from under a
        # mid-drain task (unretrieved task exceptions at loop close).
        for task in self._background:
            task.cancel()
        if self._background:
            await asyncio.gather(*self._background, return_exceptions=True)
            self._background = []
        if self._client is not None:
            await self._client.close()
        for be in self.backends:
            await be.server.close()
        for fsrv in self.slice_follower_servers:
            await fsrv.close()

    @property
    def client(self) -> TestClient:
        assert self._client is not None, "harness not started"
        return self._client

    @property
    def registry(self):
        return self._app["registry"]

    def now(self) -> float:
        return time.monotonic() - self._t0

    def active_count(self) -> int:
        return sum(1 for be in self.backends if be.active)

    # -- scaling -----------------------------------------------------------

    async def scale_to(self, n: int, drain_timeout_s: float = 5.0) -> None:
        """Scale the active replica set to ``n``.  Up: replicas join
        discovery immediately.  Down: excess replicas leave discovery,
        then DRAIN — new work is rejected at the backend while in-flight
        streams finish; the replica only counts as gone once idle."""
        assert self._discovery is not None
        n = max(0, min(n, self.num_engines))
        current = [be for be in self.backends if be.active]
        if n > len(current):
            for be in self.backends:
                if not be.active and n > len(current):
                    be.state.draining = False  # re-join after an earlier drain
                    be.active = True
                    self._discovery.add(be.url, [MODEL])
                    current.append(be)
        elif n < len(current):
            victims = current[n:]
            for be in victims:
                # k8s ordering: endpoint leaves the Service FIRST (no new
                # routing picks), preStop /drain second.
                self._discovery.remove(be.url)
            # Let racing routing decisions (endpoint list snapshots taken
            # before the removal) land before the backend starts 503ing.
            await asyncio.sleep(0.05)
            for be in victims:
                async with self.client.session.post(f"{be.url}/drain") as resp:
                    await resp.read()
            deadline = time.monotonic() + drain_timeout_s
            for be in victims:
                while be.state.num_running > 0 and time.monotonic() < deadline:
                    await asyncio.sleep(0.01)
                be.active = False
        self.active_timeline.append((self.now(), self.active_count()))

    def scale_to_background(self, n: int) -> asyncio.Task:
        """Fire a scale event without blocking the caller (the arrival
        process must not stall on a drain wait — k8s scales down
        asynchronously too).  The task is held and awaited by
        wait_background()."""
        task = asyncio.ensure_future(self.scale_to(n))
        self._background.append(task)
        return task

    async def wait_background(self, timeout_s: float = 10.0) -> None:
        """Drain outstanding background scale events (call before
        report()/oracle math — a still-pending drain means the capacity
        timeline is not final)."""
        if self._background:
            await asyncio.wait(self._background, timeout=timeout_s)
            self._background = []

    # -- faults ------------------------------------------------------------

    def inject(self, index: int, kind: str, **params) -> None:
        self.backends[index].state.inject(kind, **params)
        self.fault_timeline.append((self.now(), index, True))

    def clear_injection(self, index: int, kind: str) -> None:
        self.backends[index].state.clear_injection(kind)
        self.fault_timeline.append((self.now(), index, False))

    def kill_slice_member(self, ordinal: int) -> None:
        """Kill one follower of the fake slice group: its acks freeze,
        the leader's /health fails within the member-timeout window, and
        the slice's data plane starts refusing (the fatal-exited leader
        as the router sees it).  The whole slice — one endpoint, backend
        0 — contributes zero oracle capacity while failed."""
        assert self.slice_group is not None, "harness has no slice group"
        self.slice_group.kill_member(ordinal)
        self.fault_timeline.append((self.now(), 0, True))

    def restart_slice(self) -> None:
        """The parallel k8s group restart: members revive into one fresh
        incarnation with a STRICTLY larger epoch and the endpoint serves
        again (the breaker's half-open probe re-admits it)."""
        assert self.slice_group is not None, "harness has no slice group"
        self.slice_group.restart()
        self.backends[0].state.draining = False
        self.fault_timeline.append((self.now(), 0, False))

    # -- traffic -----------------------------------------------------------

    async def one_request(
        self, *, phase: str = "replay", priority: Optional[int] = None,
        max_tokens: Optional[int] = None,
    ) -> Outcome:
        """One streamed chat completion through the router, classified."""
        arrived = self.now()
        body = {
            "model": MODEL,
            "stream": True,
            "max_tokens": max_tokens if max_tokens is not None else self.max_tokens,
            "messages": [
                {"role": "user", "content": f"fleet probe {self.rng.random():.8f}"}
            ],
        }
        if priority is not None:
            body["priority"] = priority
        chunks = 0
        token_times: List[float] = []
        saw_done = False
        started = False
        status = 0
        try:
            resp = await self.client.post("/v1/chat/completions", json=body)
            status = resp.status
            if status != 200:
                payload = await resp.read()
                kind = self._classify_reject(status, payload)
                return self._record(
                    Outcome(arrived, self.now(), kind, status=status, phase=phase)
                )
            buf = b""
            async for chunk in resp.content.iter_any():
                started = True
                buf += chunk
                while b"\n\n" in buf:
                    frame, buf = buf.split(b"\n\n", 1)
                    if not frame.startswith(b"data: "):
                        continue
                    if frame[6:].strip() == b"[DONE]":
                        saw_done = True
                    else:
                        chunks += 1
                        token_times.append(time.monotonic())
        except Exception:
            kind = "dropped" if started else "error"
            return self._record(
                Outcome(arrived, self.now(), kind, status=status,
                        chunks=chunks, phase=phase)
            )
        if not saw_done:
            return self._record(
                Outcome(arrived, self.now(), "dropped", status=status,
                        chunks=chunks, phase=phase)
            )
        gaps = sorted(b - a for a, b in zip(token_times, token_times[1:]))
        p95 = gaps[int(0.95 * (len(gaps) - 1))] if gaps else 0.0
        return self._record(
            Outcome(arrived, self.now(), "completed", status=200,
                    chunks=chunks, itl_p95=p95, phase=phase)
        )

    async def one_embed_request(
        self, *, phase: str = "replay", texts: Optional[List[str]] = None,
        repeat_pool: int = 0,
    ) -> Outcome:
        """One /v1/embeddings request through the router's encode lane,
        classified with the same Outcome vocabulary as generation.
        ``repeat_pool`` > 0 draws inputs from a small fixed pool (the
        repeat-heavy trace the semantic cache exists for) instead of
        unique probe strings."""
        arrived = self.now()
        if texts is None:
            if repeat_pool > 0:
                texts = [f"embed corpus doc {self.rng.randrange(repeat_pool)}"]
            else:
                texts = [f"embed probe {self.rng.random():.8f}"]
        status = 0
        try:
            resp = await self.client.post(
                "/v1/embeddings", json={"model": MODEL, "input": texts}
            )
            status = resp.status
            payload = await resp.read()
        except Exception:
            return self._record(
                Outcome(arrived, self.now(), "error", status=status,
                        phase=phase)
            )
        if status != 200:
            kind = self._classify_reject(status, payload)
            return self._record(
                Outcome(arrived, self.now(), kind, status=status, phase=phase)
            )
        data = json.loads(payload).get("data", [])
        kind = "completed" if len(data) == len(texts) else "error"
        return self._record(
            Outcome(arrived, self.now(), kind, status=status,
                    chunks=len(data), phase=phase)
        )

    @staticmethod
    def _classify_reject(status: int, payload: bytes) -> str:
        if status != 429:
            return "error"
        try:
            err = json.loads(payload).get("error", {})
        except (ValueError, AttributeError):
            err = {}
        return (
            "shed_router" if err.get("type") == "fleet_overloaded"
            else "shed_engine"
        )

    def _record(self, outcome: Outcome) -> Outcome:
        self.outcomes.append(outcome)
        return outcome

    def qps_at(self, t: float, duration: float, base: float, peak: float) -> float:
        """The diurnal rate curve: base at the edges, peak mid-replay
        (half-cosine — one compressed day)."""
        frac = 0.5 * (1.0 - math.cos(2.0 * math.pi * min(1.0, max(0.0, t / duration))))
        return base + (peak - base) * frac

    async def replay(
        self,
        *,
        duration_s: float,
        base_qps: float,
        peak_qps: float,
        events: Optional[List[Tuple[float, Callable]]] = None,
        phase: str = "replay",
        low_priority_frac: float = 0.0,
        embed_frac: float = 0.0,
        embed_repeat_pool: int = 0,
    ) -> None:
        """Seeded diurnal replay.  ``events`` is a list of
        ``(replay_t, async_callable)`` fired in order as the replay
        clock passes each time (scale events, fault injections).
        ``embed_frac`` sends that fraction of arrivals down the encode
        lane (/v1/embeddings) instead of chat — the mixed
        generation+embed workload the per-lane admission contract is
        about; ``embed_repeat_pool`` makes the embed side repeat-heavy
        (semantic-cache fodder)."""
        events = sorted(events or [], key=lambda e: e[0])
        tasks: List[asyncio.Task] = []
        t_start = self.now()
        next_event = 0

        def rel() -> float:
            return self.now() - t_start

        first_rate = self.qps_at(0.0, duration_s, base_qps, peak_qps)
        t_next_arrival = (
            self.rng.expovariate(first_rate) if first_rate > 0 else duration_s
        )
        while True:
            t = rel()
            if t >= duration_s:
                break
            while next_event < len(events) and events[next_event][0] <= t:
                await events[next_event][1]()
                next_event += 1
            if t >= t_next_arrival:
                if embed_frac and self.rng.random() < embed_frac:
                    coro = self.one_embed_request(
                        phase=phase, repeat_pool=embed_repeat_pool
                    )
                else:
                    priority = (
                        1
                        if low_priority_frac
                        and self.rng.random() < low_priority_frac
                        else None
                    )
                    coro = self.one_request(phase=phase, priority=priority)
                tasks.append(asyncio.ensure_future(coro))
                rate = self.qps_at(t, duration_s, base_qps, peak_qps)
                t_next_arrival = t + (
                    self.rng.expovariate(rate) if rate > 0 else duration_s
                )
                continue
            wake = min(
                t_next_arrival,
                duration_s,
                events[next_event][0] if next_event < len(events) else duration_s,
            )
            await asyncio.sleep(max(0.001, min(wake - t, 0.25)))
        # Fire any remaining events (e.g. a trailing scale-down) before
        # waiting out the in-flight tail.
        while next_event < len(events):
            await events[next_event][1]()
            next_event += 1
        if tasks:
            await asyncio.wait(tasks, timeout=30.0)

    async def warmup(self, *, burst: int = 0, duration_s: float = 1.0) -> None:
        """Teach the capacity model each ACTIVE backend's bound: a short
        saturating burst whose engine 429s clamp the per-backend slot
        estimates (outcomes labeled phase="warmup" so measured-replay
        assertions exclude them).  This is the steady state a production
        fleet reaches after its first minutes of traffic."""
        n = burst or (self.active_count() * (self.capacity + self.max_queued) * 2)
        tasks = [
            asyncio.ensure_future(self.one_request(phase="warmup"))
            for _ in range(n)
        ]
        await asyncio.wait(tasks, timeout=max(duration_s * 10, 10.0))

    # -- analysis ----------------------------------------------------------

    def report(self, phase: str = "replay") -> Dict[str, object]:
        outs = [o for o in self.outcomes if o.phase == phase]
        by_kind: Dict[str, int] = {}
        for o in outs:
            by_kind[o.kind] = by_kind.get(o.kind, 0) + 1
        completed = [o for o in outs if o.kind == "completed"]
        itl = sorted(o.itl_p95 for o in completed if o.itl_p95 > 0)
        return {
            "total": len(outs),
            "completed": by_kind.get("completed", 0),
            "shed_router": by_kind.get("shed_router", 0),
            "shed_engine": by_kind.get("shed_engine", 0),
            "error": by_kind.get("error", 0),
            "dropped": by_kind.get("dropped", 0),
            "admitted_itl_p95_s": (
                itl[int(0.95 * (len(itl) - 1))] if itl else 0.0
            ),
        }

    def per_engine_rate(self) -> float:
        """Nominal full-throughput request rate of ONE replica: the fake
        engine's token throughput is capacity-bound (token intervals
        stretch with oversubscription), so rate = capacity * tps / tokens
        once TTFT is amortized."""
        service_s = self.ttft + self.max_tokens / self.tokens_per_sec
        return self.capacity / service_s

    def _active_at(self, t: float) -> int:
        n = self.active_timeline[0][1] if self.active_timeline else 0
        for ts, count in self.active_timeline:
            if ts <= t:
                n = count
            else:
                break
        return n

    def _faulted_at(self, t: float) -> int:
        """Engines with an armed fault at replay time ``t``."""
        armed: Dict[int, bool] = {}
        for ts, idx, on in self.fault_timeline:
            if ts <= t:
                armed[idx] = on
        return sum(1 for on in armed.values() if on)

    def oracle_admitted(
        self, phase: str = "replay", bin_s: float = 0.5,
        derate: float = 1.0,
    ) -> float:
        """The capacity-model-PERFECT admission schedule's goodput: per
        arrival-time bin, min(offered, active_capacity) requests — an
        omniscient router admitting exactly what the active replicas can
        serve and shedding the rest at zero cost.  ``derate`` scales the
        nominal per-replica rate (CI CPUs are not lab-quiet)."""
        outs = [o for o in self.outcomes if o.phase == phase]
        if not outs:
            return 0.0
        t_max = max(o.arrived_t for o in outs)
        t_min = min(o.arrived_t for o in outs)
        rate = self.per_engine_rate() * derate
        total = 0.0
        t = t_min
        while t < t_max + bin_s:
            offered = sum(1 for o in outs if t <= o.arrived_t < t + bin_s)
            mid = t + bin_s / 2
            healthy = max(0, self._active_at(mid) - self._faulted_at(mid))
            cap = healthy * rate * bin_s
            total += min(float(offered), cap)
            t += bin_s
        return total

    def shed_ordering_violations(
        self, phase: str = "replay", window_s: float = 1.0
    ) -> List[Outcome]:
        """Engine-side 429s NOT preceded (within ``window_s``) by a
        router-side fleet shed: the overload-firewall ordering guarantee
        says this list is empty — the router always sheds first, the
        engines' own bounds are the belt-and-braces layer behind it."""
        outs = [o for o in self.outcomes if o.phase == phase]
        router_shed_times = sorted(
            o.done_t for o in outs if o.kind == "shed_router"
        )
        violations = []
        for o in outs:
            if o.kind != "shed_engine":
                continue
            ok = any(
                o.done_t - window_s <= t <= o.done_t
                for t in router_shed_times
            )
            if not ok:
                violations.append(o)
        return violations
