"""Fleet-scale multi-round-QA runner: the BASELINE.md north-star workload
(320 users x 10 rounds, 1000-token shared system prompt, growing per-user
histories) ported onto the FleetHarness so the whole routing ladder —
round-robin / session / kv_aware / kv_aware_popularity — is A/B-able in
CI with no accelerator (ROADMAP item 6; SURVEY §6, tutorials 07/08).

The fake engines run the chunk-chain prefix-cache simulation plus the
prefill cost model (testing/fake_engine.py): TTFT grows with the UNCACHED
prompt tail and stretches under oversubscription, so the three quantities
the paper's headline comparison reports — fleet KV hit rate, TTFT
percentiles, output tok/s — all respond to routing policy the way they
do on real engines:

* round-robin scatters every conversation; histories re-prefill
  everywhere (hit-rate floor).
* session affinity keeps each user sticky but places users by hash —
  load-blind, so hot backends stretch TTFT; and every backend
  cold-prefills the shared system prompt once.
* kv_aware's single-owner LRU flip-flops ownership of the SHARED chain
  head (every user's chunk 0), so deep tail matches break at the head
  and users scatter under load.
* kv_aware_popularity serves the hot shared prefix from a load-grown
  replica set while tails stay session-sticky — the concentration +
  balance the tentpole claims.

``fleet KV hit rate`` here is ground truth read directly from the fake
engines' token-weighted counters (sum hit / sum query), the same numbers
the router scrapes through ``tpu:prefix_cache_{hit,query}_tokens_total``.
"""

from __future__ import annotations

import dataclasses
import importlib.util
import json
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple

from production_stack_tpu.testing.fake_engine import fake_prefix_chain
from production_stack_tpu.testing.fleet import FleetHarness

# --routing-logic value + extra router argv per ladder rung.  The
# popularity rung carries its tuned knobs: strong per-user tail
# stickiness (tradeoff 10) with a low shared-credit cap (0.17), so the
# hot head replicates onto a new member once every current member queues
# ~2 deep (tradeoff x cap) while user histories stay pinned.
ROUTING_LADDER: Dict[str, Tuple[str, Tuple[str, ...]]] = {
    "roundrobin": ("roundrobin", ()),
    "session": ("session", ("--session-key", "x-user-id")),
    "kv_aware": ("kv_aware", ()),
    "kv_aware_popularity": (
        "kv_aware_popularity",
        ("--kv-affinity-tradeoff", "10",
         "--kv-popularity-hot-credit-cap", "0.17",
         "--kv-popularity-max-replicas", "12"),
    ),
}


def load_multi_round_module():
    """Import benchmarks/multi_round_qa/multi_round_qa.py (not a package)
    by file path — shared by the tier-1 test and bench.py."""
    import sys

    existing = sys.modules.get("multi_round_qa")
    if existing is not None and hasattr(existing, "run_benchmark"):
        return existing
    path = (
        Path(__file__).resolve().parents[2]
        / "benchmarks" / "multi_round_qa" / "multi_round_qa.py"
    )
    spec = importlib.util.spec_from_file_location("multi_round_qa", path)
    assert spec is not None and spec.loader is not None
    mod = importlib.util.module_from_spec(spec)
    # dataclass processing resolves the module through sys.modules; it
    # must be registered before exec.
    sys.modules["multi_round_qa"] = mod
    spec.loader.exec_module(mod)
    return mod


@dataclasses.dataclass
class MultiRoundFleetConfig:
    """CI-scaled rendition of the canonical workload (BASELINE.md: 320
    users x 10 rounds at 1000-token shared prompt; here shrunk to run in
    seconds while keeping the shape — many users per backend, a shared
    head every request re-sends, per-user tails that grow each round)."""

    num_engines: int = 12
    # NOT a multiple of num_engines: a user count divisible by the fleet
    # size makes round-robin accidentally session-sticky (the rotation
    # phase re-maps every user to the same engine each round) and the
    # baseline stops being a baseline.
    num_users: int = 26
    num_rounds: int = 5
    qps: float = 28.0
    system_prompt_len: int = 1000   # words of the SHARED head (~3k chars)
    user_info_len: int = 600        # words of per-user context (the tail)
    answer_len: int = 16            # fake tokens per round
    # Heterogeneous load: every k-th user streams long answers (real QA
    # answer lengths vary hugely) — the axis that separates load-aware
    # placement from hash placement: two heavy users hashed onto one
    # backend is a sustained hot pocket session affinity never repairs.
    heavy_answer_len: int = 96
    heavy_every: int = 4
    seed: int = 0
    # Fake-engine service model.  Deliberately SLOW simulated clock
    # (chunky token intervals, tens-of-ms prefill costs): TTFT signals
    # must dominate asyncio-loop scheduling noise for seeded percentile
    # comparisons to be stable in CI.
    capacity: int = 2
    max_queued: int = 16
    tokens_per_sec: float = 40.0
    ttft: float = 0.03
    prefill_chars_per_sec: float = 20000.0
    prefix_chunk_chars: int = 64
    # Spread user joins over this window (s): the canonical 320-user run
    # ramps users up over minutes; a continuous arrival stream is what
    # load-aware placement exploits (None = legacy one-gap stagger).
    join_window_s: Optional[float] = 4.0
    # Fixed backend ports: consistent-hash placement (the session arm)
    # hashes backend URLs, so ephemeral ports would re-roll session's
    # user placement every run and the seeded A/B would not be an A/B.
    base_port: int = 19360
    # Shared KV store across the fleet (the PR-4 plane, simulated):
    # computed chunks export; store-resident chunks import at ~4x the
    # prefill rate and count as cache hits (the prefetch plane lands
    # imports in the prefix cache before schedule).  OFF for the ladder
    # A/B — a fleet-wide store makes every policy's misses into imports
    # and the hit-rate axis stops discriminating routing; the bench adds
    # a dedicated popularity+store rung to show the warming win.
    shared_store: bool = False
    request_timeout: float = 30.0


def shared_prefix_digests(mod, config, chunk_chars: int) -> List[str]:
    """The chunk digests every user's round-1 prompt shares (the system-
    prompt head as the fake engines hash it): build two users' round-1
    prompt texts exactly as the workload will, take the common prefix,
    and chain-hash the fully-shared chunks."""
    u1 = mod.UserSession(config.init_user_id + 1, config)
    u2 = mod.UserSession(config.init_user_id + 2, config)
    t1 = json.dumps([{"role": "user", "content": u1._round_prompt(1)}])
    t2 = json.dumps([{"role": "user", "content": u2._round_prompt(1)}])
    common = 0
    for a, b in zip(t1, t2):
        if a != b:
            break
        common += 1
    n = common // chunk_chars
    return fake_prefix_chain(t1, chunk_chars)[:n]


async def run_fleet_multi_round(
    policy: str,
    cfg: Optional[MultiRoundFleetConfig] = None,
    router_args: Sequence[str] = (),
) -> Dict[str, object]:
    """One ladder rung: FleetHarness fleet + the multi-round-QA workload,
    measured on fleet KV hit rate / TTFT percentiles / output tok/s /
    shared-prefix residency."""
    cfg = cfg or MultiRoundFleetConfig()
    routing_logic, policy_args = ROUTING_LADDER[policy]
    mod = load_multi_round_module()

    engine_kwargs: Dict[str, object] = {
        "prefix_chunk_chars": cfg.prefix_chunk_chars,
        "prefill_chars_per_sec": cfg.prefill_chars_per_sec,
        "prefill_scales_with_load": True,
    }
    if cfg.shared_store:
        engine_kwargs["shared_store"] = set()   # ONE set for the fleet
        engine_kwargs["remote_store_import"] = True

    h = FleetHarness(
        num_engines=cfg.num_engines,
        seed=cfg.seed,
        capacity=cfg.capacity,
        max_queued=cfg.max_queued,
        tokens_per_sec=cfg.tokens_per_sec,
        ttft=cfg.ttft,
        max_tokens=cfg.answer_len,
        routing_logic=routing_logic,
        # Fleet admission stays out of the ladder comparison: the A/B
        # isolates ROUTING; admission on/off is fleet_surge_ab's axis.
        fleet_admission=False,
        router_args=tuple(policy_args) + tuple(router_args),
        engine_kwargs=engine_kwargs,
        base_port=cfg.base_port,
    )
    await h.start(active=cfg.num_engines)
    try:
        wl = mod.WorkloadConfig(
            base_url=str(h._router_server.make_url("")).rstrip("/"),
            model="fleet/fake-llama",
            num_users=cfg.num_users,
            num_rounds=cfg.num_rounds,
            qps=cfg.qps,
            system_prompt_len=cfg.system_prompt_len,
            user_info_len=cfg.user_info_len,
            answer_len=cfg.answer_len,
            heavy_answer_len=cfg.heavy_answer_len,
            heavy_every=cfg.heavy_every,
            request_timeout=cfg.request_timeout,
            join_window=cfg.join_window_s,
        )
        result = await mod.run_benchmark(wl)
        summary = result["summary"]
        records = result["records"]

        hit = sum(be.state.prefix_hit_tokens for be in h.backends)
        query = sum(be.state.prefix_query_tokens for be in h.backends)
        shared = shared_prefix_digests(mod, wl, cfg.prefix_chunk_chars)
        resident = 0
        if shared:
            # The DEEPEST fully-shared chunk proves the whole shared head
            # resident on a backend (digests chain).
            resident = sum(
                1 for be in h.backends if shared[-1] in be.state._seen_chunks
            )
        ttfts = sorted(r.ttft for r in records if r.error is None)

        def pct(p: float) -> float:
            if not ttfts:
                return 0.0
            return ttfts[min(len(ttfts) - 1, round(p / 100 * (len(ttfts) - 1)))]

        out: Dict[str, object] = {
            "policy": policy,
            "requests": summary["requests_finished"],
            "failed": summary["requests_failed"],
            "kv_hit_rate": round(hit / query, 4) if query else 0.0,
            "ttft_p50_ms": round(pct(50) * 1e3, 1),
            "ttft_p95_ms": round(pct(95) * 1e3, 1),
            "output_tok_s": summary["output_tokens_per_s"],
            "shared_prefix_backends": resident,
            # Raw samples + token totals so callers can POOL repeated
            # runs into one percentile estimate (bench.py runs each arm
            # twice — pooled p50 halves the CI loop-noise variance).
            "ttft_samples": [round(t, 5) for t in ttfts],
            "hit_tokens": int(hit),
            "query_tokens": int(query),
        }
        router_obj = h.registry.get("routing_logic")
        if hasattr(router_obj, "popularity_snapshot"):
            out["popularity"] = router_obj.popularity_snapshot()
        return out
    finally:
        await h.close()
