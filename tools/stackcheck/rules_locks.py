"""Rule family SC5 — lock discipline and shared-state races.

The stack runs seven-plus cooperating thread roots (engine step loop,
prefetch fetchers, offload stager writer, remote-KV deleter, prefix
exporter, plus the asyncio event loop in each server process) against
~15 ad-hoc lock sites, and PRs 4–6 each shipped a review-caught race.
This family turns the locking conventions into checks:

SC501  a module/instance attribute is mutated from >=2 distinct thread
       roots with no lock held in common across the mutation sites.
SC502  a blocking call (the SC1xx deny list / kvserver RPC surface) is
       made while a lock is held — every other thread contending for
       that lock inherits the full wait.
SC503  lock-acquisition-order cycle across the call graph (deadlock
       potential, e.g. A->B in one thread and B->A in another).

Thread attribution: ``# stackcheck: thread=<name>`` marks a function as
the entry point (``target=``) of a named OS thread; everything reachable
from it in the call graph runs (at least sometimes) on that thread.
``async def``s are implicitly attributed to the ``asyncio-loop`` thread.
Lock identity is intra-class: ``self._lock`` inside class ``C`` is the
lock ``module:C._lock``; ``threading.Condition(self._lock)`` aliases the
condition to the lock it wraps.  Attributes holding intrinsically
thread-safe objects (queue.Queue, threading.Event, locks themselves) are
exempt from SC501 — their mutation API is the synchronization.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from tools.stackcheck import config as C
from tools.stackcheck.callgraph import CallGraph, FuncInfo
from tools.stackcheck.core import Violation
from tools.stackcheck.core import self_attr_name as _self_attr
from tools.stackcheck.rules_blocking import _blocking_reason, dotted_name

ASYNCIO_THREAD = "asyncio-loop"

# Constructor basenames establishing lock identity on a self attribute.
_LOCK_CTORS = ("Lock", "RLock", "Semaphore", "BoundedSemaphore")
_COND_CTORS = ("Condition",)
# Attributes holding these are intrinsically thread-safe: their mutation
# API is the synchronization (and Event.set()/clear() are atomic).
_THREADSAFE_CTORS = (
    "Queue", "LifoQueue", "PriorityQueue", "SimpleQueue", "Event",
) + _LOCK_CTORS + _COND_CTORS

# Method basenames that mutate their receiver in place.
_MUTATOR_NAMES = (
    "append", "extend", "insert", "add", "update", "pop", "popitem",
    "remove", "discard", "clear", "setdefault",
)

# Condition methods that RELEASE the lock while waiting — not blocking
# "under" the lock in the SC502 sense.
_LOCK_RELEASING_WAITS = ("wait", "wait_for")


@dataclasses.dataclass
class ClassLocks:
    """Lock layout of one class: attr -> canonical lock id, plus the
    attrs exempt from SC501 because their values are thread-safe."""

    locks: Dict[str, str] = dataclasses.field(default_factory=dict)
    threadsafe_attrs: Set[str] = dataclasses.field(default_factory=set)


@dataclasses.dataclass
class Mutation:
    attr: str
    line: int
    held: FrozenSet[str]
    func: str  # qualname


@dataclasses.dataclass
class LockedCall:
    node: ast.Call
    held: FrozenSet[str]


@dataclasses.dataclass
class FuncLockFacts:
    mutations: List[Mutation] = dataclasses.field(default_factory=list)
    calls: List[LockedCall] = dataclasses.field(default_factory=list)
    # (held lock, acquired lock, line) for directly nested acquisitions.
    nested_acquires: List[Tuple[str, str, int]] = dataclasses.field(
        default_factory=list
    )
    # Every lock this function acquires directly (for closure propagation).
    acquired: Set[str] = dataclasses.field(default_factory=set)
    # line anchors for acquisitions (lock id -> first line).
    acquire_lines: Dict[str, int] = dataclasses.field(default_factory=dict)


def _ctor_basename(value: ast.expr) -> Optional[str]:
    if isinstance(value, ast.Call):
        return dotted_name(value.func).rsplit(".", 1)[-1]
    return None


def collect_class_locks(graph: CallGraph) -> Dict[Tuple[str, str], ClassLocks]:
    """(module, class) -> lock layout, from `self.X = threading.Lock()`
    style assignments (plain or annotated) anywhere in the class's
    methods."""
    out: Dict[Tuple[str, str], ClassLocks] = {}
    for info in graph.functions.values():
        if info.cls is None:
            continue
        key = (info.module, info.cls)
        layout = out.setdefault(key, ClassLocks())
        for node in ast.walk(info.node):
            # `self._lock: threading.Lock = threading.Lock()` declares a
            # lock just as much as the unannotated form — missing the
            # AnnAssign shape would manufacture phantom SC501s on state
            # the lock correctly guards (and silently exempt it from
            # SC502/SC503).
            target: ast.expr
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                target, value = node.targets[0], node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                target, value = node.target, node.value
            else:
                continue
            attr = _self_attr(target)
            if attr is None:
                continue
            ctor = _ctor_basename(value)
            if ctor is None:
                continue
            canon = f"{info.module}:{info.cls}.{attr}"
            if ctor in _COND_CTORS:
                alias: Optional[str] = None
                if isinstance(value, ast.Call) and value.args:
                    wrapped = _self_attr(value.args[0])
                    if wrapped is not None:
                        alias = f"{info.module}:{info.cls}.{wrapped}"
                layout.locks[attr] = alias or canon
                layout.threadsafe_attrs.add(attr)
            elif ctor in _LOCK_CTORS:
                layout.locks[attr] = canon
                layout.threadsafe_attrs.add(attr)
            elif ctor in _THREADSAFE_CTORS:
                layout.threadsafe_attrs.add(attr)
    return out


class _LockWalker:
    """Intra-procedural walk tracking the set of held locks.  Nested
    function/lambda bodies are skipped: they execute on whatever thread
    later calls them, not at the point of definition."""

    def __init__(self, info: FuncInfo, layout: ClassLocks) -> None:
        self.info = info
        self.layout = layout
        self.facts = FuncLockFacts()

    def run(self) -> FuncLockFacts:
        for stmt in self.info.node.body:
            self._visit(stmt, frozenset())
        return self.facts

    # -- helpers -----------------------------------------------------------

    def _lock_of(self, expr: ast.expr) -> Optional[str]:
        attr = _self_attr(expr)
        if attr is None:
            return None
        return self.layout.locks.get(attr)

    def _record_mutation(self, attr: Optional[str], line: int,
                         held: FrozenSet[str]) -> None:
        if attr is None or attr in self.layout.threadsafe_attrs:
            return
        self.facts.mutations.append(
            Mutation(attr=attr, line=line, held=held,
                     func=self.info.qualname)
        )

    def _mutation_targets(self, target: ast.expr) -> List[Optional[str]]:
        if isinstance(target, (ast.Tuple, ast.List)):
            out: List[Optional[str]] = []
            for elt in target.elts:
                out.extend(self._mutation_targets(elt))
            return out
        if isinstance(target, ast.Subscript):
            return [_self_attr(target.value)]
        if isinstance(target, ast.Starred):
            return self._mutation_targets(target.value)
        return [_self_attr(target)]

    # -- walk --------------------------------------------------------------

    def _visit(self, node: ast.AST, held: FrozenSet[str]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return  # deferred execution: not on this thread/lock scope
        if isinstance(node, (ast.With, ast.AsyncWith)):
            acquired = set()
            for item in node.items:
                lock = self._lock_of(item.context_expr)
                if lock is not None:
                    self.facts.acquired.add(lock)
                    self.facts.acquire_lines.setdefault(
                        lock, item.context_expr.lineno
                    )
                    for h in held:
                        if h != lock:
                            self.facts.nested_acquires.append(
                                (h, lock, item.context_expr.lineno)
                            )
                    acquired.add(lock)
                self._visit(item.context_expr, held)
            inner = held | acquired
            for stmt in node.body:
                self._visit(stmt, inner)
            return
        if isinstance(node, ast.Assign):
            if self._expr_has_call(node.value):
                self._visit(node.value, held)
            for tgt in node.targets:
                for attr in self._mutation_targets(tgt):
                    self._record_mutation(attr, node.lineno, held)
                self._visit_stores_only(tgt, held)
            return
        if isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            if node.value is not None:
                self._visit(node.value, held)
            for attr in self._mutation_targets(node.target):
                self._record_mutation(attr, node.lineno, held)
            return
        if isinstance(node, ast.Delete):
            for tgt in node.targets:
                for attr in self._mutation_targets(tgt):
                    self._record_mutation(attr, node.lineno, held)
            return
        if isinstance(node, ast.Call):
            self.facts.calls.append(LockedCall(node=node, held=held))
            # In-place mutator methods on a self attribute.
            fn = node.func
            if (
                isinstance(fn, ast.Attribute)
                and fn.attr in _MUTATOR_NAMES
            ):
                self._record_mutation(
                    _self_attr(fn.value), node.lineno, held
                )
        for child in ast.iter_child_nodes(node):
            self._visit(child, held)

    def _visit_stores_only(self, node: ast.AST, held: FrozenSet[str]) -> None:
        # Subscript targets contain value expressions (indices) that may
        # call things; walk them for call tracking.
        for child in ast.iter_child_nodes(node):
            self._visit(child, held)

    @staticmethod
    def _expr_has_call(node: ast.AST) -> bool:
        return any(isinstance(n, ast.Call) for n in ast.walk(node))


def thread_reach(graph: CallGraph, cfg: C.Config) -> Dict[str, Set[str]]:
    """thread name -> set of qualnames attributed to that thread.

    Explicit roots come from ``thread=`` annotations; every ``async def``
    is an implicit root of the asyncio-loop thread.  Attribution follows
    the call graph (including the configured callback edges)."""
    roots_by_thread: Dict[str, List[str]] = {}
    for q, name in graph.find_thread_roots().items():
        roots_by_thread.setdefault(name, []).append(q)
    async_roots = [
        q for q, info in graph.functions.items() if info.is_async
    ]
    if async_roots:
        roots_by_thread.setdefault(ASYNCIO_THREAD, []).extend(async_roots)
    # The close plane is reached through dynamic hops the AST cannot
    # resolve (asyncio.to_thread(self.engine.close) passes a function
    # REFERENCE; generic `.close()` attr calls are too ambiguous for
    # by-name resolution) — without the declared lifecycle edges,
    # LLMEngine.close and everything under it would be attributed to no
    # thread at all and SC501/SC502 would go silent on exactly the
    # concurrency-sensitive shutdown code.
    extra: Dict[str, List[str]] = {
        k: list(v) for k, v in cfg.extra_edges.items()
    }
    for q, callees in graph.expand_suffix_edges(
        cfg.lifecycle_extra_edges
    ).items():
        extra.setdefault(q, []).extend(callees)
    out: Dict[str, Set[str]] = {}
    for name, roots in roots_by_thread.items():
        # Strict (typed) edges only: a by-name guess on a generic method
        # (`get`, `put`, `update`) would attribute another process's code
        # to this thread and manufacture races that cannot happen.
        out[name] = set(graph.reachable(
            roots, extra_edges=extra, strict=True
        ))
    return out


def _blocking_reason_for_locks(
    call: ast.Call, graph: CallGraph, info: FuncInfo
) -> str:
    """Why this call blocks while a lock is held ('' = it doesn't)."""
    why = _blocking_reason(call)
    if why:
        fn = call.func
        if (
            isinstance(fn, ast.Attribute)
            and fn.attr in _LOCK_RELEASING_WAITS
        ):
            return ""
        return why
    fn = call.func
    if isinstance(fn, ast.Attribute):
        if fn.attr in _LOCK_RELEASING_WAITS:
            return ""
        if fn.attr in C.ASYNC_CONTRACT_NAMES:
            return dotted_name(fn)
    # Strict resolution only, like every other SC5 edge: a by-name guess
    # on an untyped receiver (`self.x.delete(...)` where x's class is
    # unknown) would match the kvserver client surface and manufacture a
    # phantom blocking-under-lock finding.
    for target in graph._resolve_call(call, info, ambiguous=False):
        if any(target.endswith(sfx) for sfx in C.BLOCKING_CONTRACT_SUFFIXES):
            return target.split(":", 1)[-1]
    return ""


def check_locks(graph: CallGraph, cfg: C.Config) -> List[Violation]:
    out: List[Violation] = []
    layouts = collect_class_locks(graph)
    reach = thread_reach(graph, cfg)

    facts: Dict[str, FuncLockFacts] = {}
    for q, info in graph.functions.items():
        layout = layouts.get((info.module, info.cls or ""), ClassLocks())
        facts[q] = _LockWalker(info, layout).run()

    # Locks held at EVERY (typed-resolved) call site propagate into the
    # callee: a helper only ever invoked under the lock
    # (HostOffloadManager._evict_oldest) is as guarded as its callers.
    # Thread roots and async defs are entered lock-free by the runtime,
    # so they never inherit anything.
    callers: Dict[str, List[Tuple[str, FrozenSet[str]]]] = {}
    for q, info in graph.functions.items():
        for lc in facts[q].calls:
            for target in graph._resolve_call(lc.node, info, ambiguous=False):
                callers.setdefault(target, []).append((q, lc.held))
    lock_free_entries = set(graph.find_thread_roots())
    lock_free_entries.update(
        q for q, info in graph.functions.items() if info.is_async
    )
    for callees in cfg.extra_edges.values():
        for sfx in callees:
            lock_free_entries.update(
                q for q in graph.functions if q.endswith(sfx)
            )
    all_locks = frozenset().union(*[f.acquired for f in facts.values()]) \
        if facts else frozenset()
    # The optimistic all_locks seed only drains through a call chain
    # that starts at a lock-free entry (or an uncalled function, which
    # is entered lock-free by definition).  A call-graph cycle with no
    # such chain into it — e.g. a self-recursive retry helper nobody
    # calls — would keep all_locks forever, manufacturing SC502s and
    # masking SC501s; it is dead code in the strict graph, so seed it
    # lock-free instead.
    zero_seeded = {
        q for q in graph.functions
        if q not in callers or q in lock_free_entries
    }
    fwd: Dict[str, Set[str]] = {}
    for callee, sites in callers.items():
        for caller, _ in sites:
            fwd.setdefault(caller, set()).add(callee)
    entered = set(zero_seeded)
    work = list(zero_seeded)
    while work:
        for callee in fwd.get(work.pop(), ()):
            if callee not in entered:
                entered.add(callee)
                work.append(callee)
    entry_held: Dict[str, FrozenSet[str]] = {
        q: (
            all_locks
            if q in entered and q not in zero_seeded
            else frozenset()
        )
        for q in graph.functions
    }
    changed = True
    while changed:
        changed = False
        for q, sites in callers.items():
            if q not in entered or q in lock_free_entries:
                continue
            new = frozenset.intersection(*[
                held | entry_held[caller] for caller, held in sites
            ])
            if new != entry_held[q]:
                entry_held[q] = new
                changed = True

    # -- SC501: cross-thread mutation with no common lock -------------------
    # (module, class, attr) -> mutation sites + the threads mutating them.
    by_attr: Dict[Tuple[str, str, str], List[Tuple[Mutation, Set[str]]]] = {}
    for q, info in graph.functions.items():
        if info.cls is None or info.name == "__init__":
            continue
        threads = {t for t, fns in reach.items() if q in fns}
        if not threads:
            continue  # unreachable from any thread root: cannot race
        for mut in facts[q].mutations:
            key = (info.module, info.cls, mut.attr)
            by_attr.setdefault(key, []).append((mut, threads))

    for (module, cls, attr), sites in sorted(by_attr.items()):
        all_threads: Set[str] = set()
        for _, threads in sites:
            all_threads |= threads
        if len(all_threads) < 2:
            continue
        common = frozenset.intersection(*[
            m.held | entry_held[m.func] for m, _ in sites
        ])
        if common:
            continue
        # Anchor at the first unlocked site (there must be one: with no
        # common lock, at least one site holds something the others
        # don't — prefer a site holding nothing at all).
        anchor = min(
            sites, key=lambda s: (len(s[0].held), s[0].line)
        )[0]
        info = graph.functions[anchor.func]
        func_span = (info.def_line, info.end_line)
        if info.src.allowed_at(anchor.line, "SC501", func_span):
            continue
        out.append(Violation(
            rule="SC501", file=info.src.rel, line=anchor.line,
            qualname=f"{cls}.{attr}",
            message=(
                f"`self.{attr}` is mutated from threads "
                f"{{{', '.join(sorted(all_threads))}}} with no common "
                f"lock across its {len(sites)} mutation site(s); guard "
                "every mutation with one lock or confine the attribute "
                "to a single owner thread"
            ),
            detail=f"{cls}.{attr}",
        ))

    # -- SC502: blocking call while a lock is held ---------------------------
    # Caller-propagated locks count: a helper only ever invoked under a
    # lock (entry_held) blocks its callers' lock just as surely as a
    # local `with self._lock:` does.
    for q, info in graph.functions.items():
        func_span = (info.def_line, info.end_line)
        for lc in facts[q].calls:
            held = lc.held | entry_held[q]
            if not held:
                continue
            why = _blocking_reason_for_locks(lc.node, graph, info)
            if not why:
                continue
            if info.src.allowed_at(lc.node.lineno, "SC502", func_span):
                continue
            out.append(Violation(
                rule="SC502", file=info.src.rel, line=lc.node.lineno,
                qualname=q.split(":", 1)[-1],
                message=(
                    f"blocking call `{why}` while holding "
                    f"{{{', '.join(sorted(held))}}} — every thread "
                    "contending for the lock inherits the full wait"
                ),
                detail=why,
            ))

    # -- SC503: lock-acquisition-order cycles --------------------------------
    # Locks each function's call closure can acquire, over STRICTLY
    # resolved (typed) edges only — the by-name over-approximation would
    # let a generic `.get()`/`.pop()` manufacture phantom lock edges and
    # report deadlocks that cannot happen.
    strict_edges: Dict[str, Set[str]] = graph.typed_edges
    closure_acq: Dict[str, Set[str]] = {
        q: set(f.acquired) for q, f in facts.items()
    }
    changed = True
    while changed:
        changed = False
        for q in graph.functions:
            acc = closure_acq[q]
            before = len(acc)
            for callee in strict_edges.get(q, ()):
                acc |= closure_acq.get(callee, set())
            if len(acc) != before:
                changed = True

    # order edges: (held, acquired) -> (file, line, via qualname)
    order_edges: Dict[Tuple[str, str], Tuple[str, int, str]] = {}
    for q, info in graph.functions.items():
        for held, acq, line in facts[q].nested_acquires:
            order_edges.setdefault(
                (held, acq), (info.src.rel, line, q.split(":", 1)[-1])
            )
        for lc in facts[q].calls:
            if not lc.held:
                continue
            # Strict resolution only: a by-name guess ("get", "pop") on
            # an untyped receiver would manufacture phantom lock edges
            # and report deadlocks that cannot happen.
            for target in graph._resolve_call(lc.node, info, ambiguous=False):
                for acq in closure_acq.get(target, set()):
                    for held in lc.held:
                        if held != acq:
                            order_edges.setdefault(
                                (held, acq),
                                (info.src.rel, lc.node.lineno,
                                 q.split(":", 1)[-1]),
                            )

    adj: Dict[str, Set[str]] = {}
    for (a, b) in order_edges:
        adj.setdefault(a, set()).add(b)

    seen_cycles: Set[Tuple[str, ...]] = set()
    for start in sorted(adj):
        stack = [(start, [start])]
        while stack:
            node, path = stack.pop()
            for nxt in sorted(adj.get(node, ())):
                if nxt == start:
                    cycle = tuple(sorted(set(path)))
                    if len(cycle) < 2 or cycle in seen_cycles:
                        continue
                    seen_cycles.add(cycle)
                    edge = order_edges[(node, start)]
                    file, line, via = edge
                    src = next(
                        s for s in graph.sources if s.rel == file
                    )
                    if src.allowed_at(line, "SC503"):
                        continue
                    out.append(Violation(
                        rule="SC503", file=file, line=line, qualname=via,
                        message=(
                            "lock-acquisition-order cycle "
                            f"{' -> '.join(path + [start])} (deadlock "
                            "potential: two threads taking the locks in "
                            "opposite order wedge each other); pick one "
                            "global order or drop the nested acquire"
                        ),
                        detail="<->".join(cycle),
                    ))
                elif nxt not in path and len(path) < 6:
                    stack.append((nxt, path + [nxt]))
    return out
