"""Intra-package call graph over the AST (no imports executed).

Resolution is deliberately conservative-by-overapproximation: when an
attribute call ``obj.method(...)`` cannot be typed, an edge is added to
EVERY package definition of ``method`` (capped — past the cap the name is
treated as too generic to mean anything, e.g. ``get``/``items``).  For a
reachability analysis that feeds deny-list rules this errs toward false
positives, which the inline-annotation mechanism then forces a human to
justify — the failure mode we want for invariants like "no RPC under the
scheduler" (a silent false NEGATIVE is the expensive one).

Dynamic indirections the AST cannot see (callbacks stored on attributes)
are closed over by ``extra_edges`` — e.g. the scheduler's
``offload_cb``/``restore_cb``/``remote_prefix_cb`` wiring, declared in
tools/stackcheck/config.py right next to the rule that needs them.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, Iterable, List, Optional, Set, Tuple

from tools.stackcheck.core import SourceFile

# Attribute-call basenames too generic to resolve by name alone.
_MAX_AMBIGUOUS_TARGETS = 4


@dataclasses.dataclass
class FuncInfo:
    qualname: str            # module:Class.func or module:func
    module: str              # dotted module path
    cls: Optional[str]
    name: str
    node: ast.AST            # FunctionDef | AsyncFunctionDef
    src: SourceFile
    is_async: bool

    @property
    def def_line(self) -> int:
        return self.node.lineno

    @property
    def end_line(self) -> int:
        return getattr(self.node, "end_lineno", self.node.lineno)


def _module_name(rel: str) -> str:
    mod = rel[:-3] if rel.endswith(".py") else rel
    mod = mod.replace("/", ".")
    if mod.endswith(".__init__"):
        mod = mod[: -len(".__init__")]
    return mod


class CallGraph:
    def __init__(self, sources: List[SourceFile]):
        self.sources = sources
        self.functions: Dict[str, FuncInfo] = {}
        # method name -> qualnames defining it (for attribute resolution)
        self.by_name: Dict[str, List[str]] = {}
        # class name -> {method name -> qualname}
        self.by_class: Dict[str, Dict[str, str]] = {}
        self.edges: Dict[str, Set[str]] = {}
        # per-module import alias maps: module -> {alias: dotted target}
        self._imports: Dict[str, Dict[str, str]] = {}
        self._index()
        self._build_edges()

    # -- indexing ----------------------------------------------------------

    def _index(self) -> None:
        for src in self.sources:
            mod = _module_name(src.rel)
            imports: Dict[str, str] = {}
            for node in ast.walk(src.tree):
                if isinstance(node, ast.Import):
                    for a in node.names:
                        imports[a.asname or a.name.split(".")[0]] = a.name
                elif isinstance(node, ast.ImportFrom) and node.module:
                    for a in node.names:
                        imports[a.asname or a.name] = f"{node.module}.{a.name}"
            self._imports[mod] = imports

            def add(node, cls: Optional[str]):
                q = (
                    f"{mod}:{cls}.{node.name}" if cls else f"{mod}:{node.name}"
                )
                info = FuncInfo(
                    qualname=q, module=mod, cls=cls, name=node.name,
                    node=node, src=src,
                    is_async=isinstance(node, ast.AsyncFunctionDef),
                )
                self.functions[q] = info
                self.by_name.setdefault(node.name, []).append(q)
                if cls:
                    self.by_class.setdefault(cls, {})[node.name] = q

            for node in src.tree.body:
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    add(node, None)
                elif isinstance(node, ast.ClassDef):
                    for sub in node.body:
                        if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                            add(sub, node.name)

    # -- edges -------------------------------------------------------------

    def _resolve_call(self, call: ast.Call, info: FuncInfo) -> List[str]:
        fn = call.func
        targets: List[str] = []
        if isinstance(fn, ast.Name):
            name = fn.id
            # Same-module function first.
            q = f"{info.module}:{name}"
            if q in self.functions:
                return [q]
            # from-import of a package function.
            imported = self._imports.get(info.module, {}).get(name)
            if imported:
                dotted_mod, _, attr = imported.rpartition(".")
                q = f"{dotted_mod}:{attr}"
                if q in self.functions:
                    return [q]
            # Class constructor -> __init__.
            init = self.by_class.get(name, {}).get("__init__")
            if init:
                return [init]
            return []
        if not isinstance(fn, ast.Attribute):
            return []
        attr = fn.attr
        base = fn.value
        # self.method() -> same class.
        if isinstance(base, ast.Name) and base.id in ("self", "cls") and info.cls:
            q = self.by_class.get(info.cls, {}).get(attr)
            if q:
                return [q]
            # Fall through: attribute may be a callback or inherited.
        # module.func() via import alias.
        if isinstance(base, ast.Name):
            imported = self._imports.get(info.module, {}).get(base.id)
            if imported:
                # Covers both `import pkg.module as m; m.func()` and
                # `from pkg import module; module.func()` — the import
                # table stores the full dotted module either way.
                q = f"{imported}:{attr}"
                if q in self.functions:
                    return [q]
        # Unknown receiver: by-name over-approximation.
        candidates = self.by_name.get(attr, [])
        if 0 < len(candidates) <= _MAX_AMBIGUOUS_TARGETS:
            targets.extend(candidates)
        return targets

    def _build_edges(self) -> None:
        for q, info in self.functions.items():
            outs: Set[str] = set()
            for node in ast.walk(info.node):
                if isinstance(node, ast.Call):
                    outs.update(self._resolve_call(node, info))
            outs.discard(q)
            self.edges[q] = outs

    # -- queries -----------------------------------------------------------

    def reachable(
        self,
        roots: Iterable[str],
        extra_edges: Optional[Dict[str, List[str]]] = None,
        exclude: Optional[Set[str]] = None,
    ) -> Dict[str, Tuple[str, ...]]:
        """BFS from ``roots``; returns {qualname: path-from-root} (path
        includes the qualname itself, root first).  ``extra_edges``
        injects callback edges the AST cannot see.  ``exclude`` qualnames
        (boundary annotations: legacy/gated subtrees) are never entered."""
        extra = extra_edges or {}
        excl = exclude or set()
        out: Dict[str, Tuple[str, ...]] = {}
        queue: List[Tuple[str, Tuple[str, ...]]] = [
            (r, (r,)) for r in roots if r in self.functions and r not in excl
        ]
        while queue:
            q, path = queue.pop(0)
            if q in out:
                continue
            out[q] = path
            nxt = set(self.edges.get(q, ()))
            nxt.update(extra.get(q, ()))
            for callee in sorted(nxt):
                if (
                    callee in self.functions
                    and callee not in out
                    and callee not in excl
                ):
                    queue.append((callee, path + (callee,)))
        return out

    def _annotated(self, table_name: str, kind_prefix: str) -> List[str]:
        found = []
        for q, info in self.functions.items():
            table = getattr(info.src, table_name)
            first = min(
                [info.def_line]
                + [d.lineno for d in getattr(info.node, "decorator_list", [])]
            )
            for ln in range(first - 2, info.def_line + 1):
                kind = table.get(ln)
                if kind is not None and kind.startswith(kind_prefix):
                    found.append(q)
                    break
        return sorted(found)

    def find_roots(self, kind_prefix: str = "") -> List[str]:
        """Functions annotated ``# stackcheck: root=<kind>`` on or
        directly above their def (decorator lines included)."""
        return self._annotated("roots", kind_prefix)

    def find_boundaries(self, kind_prefix: str = "") -> List[str]:
        """Functions annotated ``# stackcheck: boundary=<kind>``: gated
        legacy subtrees the reachability rules must not descend into."""
        return self._annotated("boundaries", kind_prefix)
