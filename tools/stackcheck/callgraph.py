"""Intra-package call graph over the AST (no imports executed).

Resolution is deliberately conservative-by-overapproximation: when an
attribute call ``obj.method(...)`` cannot be typed, an edge is added to
EVERY package definition of ``method`` (capped — past the cap the name is
treated as too generic to mean anything, e.g. ``get``/``items``).  For a
reachability analysis that feeds deny-list rules this errs toward false
positives, which the inline-annotation mechanism then forces a human to
justify — the failure mode we want for invariants like "no RPC under the
scheduler" (a silent false NEGATIVE is the expensive one).

Dynamic indirections the AST cannot see (callbacks stored on attributes)
are closed over by ``extra_edges`` — e.g. the scheduler's
``offload_cb``/``restore_cb``/``remote_prefix_cb`` wiring, declared in
tools/stackcheck/config.py right next to the rule that needs them.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

from tools.stackcheck.core import SourceFile
from tools.stackcheck.core import self_attr_name as _self_attr_name

# Attribute-call basenames too generic to resolve by name alone.
_MAX_AMBIGUOUS_TARGETS = 4


@dataclasses.dataclass
class FuncInfo:
    qualname: str            # module:Class.func or module:func
    module: str              # dotted module path
    cls: Optional[str]
    name: str
    node: "ast.FunctionDef | ast.AsyncFunctionDef"
    src: SourceFile
    is_async: bool

    @property
    def def_line(self) -> int:
        return self.node.lineno

    @property
    def end_line(self) -> int:
        return self.node.end_lineno or self.node.lineno


def _module_name(rel: str) -> str:
    mod = rel[:-3] if rel.endswith(".py") else rel
    mod = mod.replace("/", ".")
    if mod.endswith(".__init__"):
        mod = mod[: -len(".__init__")]
    return mod


class CallGraph:
    def __init__(self, sources: List[SourceFile]) -> None:
        self.sources = sources
        self.functions: Dict[str, FuncInfo] = {}
        # method name -> qualnames defining it (for attribute resolution)
        self.by_name: Dict[str, List[str]] = {}
        # class name -> {method name -> qualname}
        self.by_class: Dict[str, Dict[str, str]] = {}
        self.edges: Dict[str, Set[str]] = {}
        # Edges resolved WITHOUT the by-name over-approximation: only
        # same-module/import/self/typed-receiver resolutions.  Thread
        # attribution (SC5) and lock-order analysis use these — a false
        # edge there manufactures a race/deadlock out of nothing.
        self.typed_edges: Dict[str, Set[str]] = {}
        # (module, class) -> {self attr -> bare class name} inferred from
        # `self.X = ClassName(...)` ctors and annotated params/attrs.
        self.attr_types: Dict[Tuple[str, str], Dict[str, str]] = {}
        # per-module import alias maps: module -> {alias: dotted target}
        self._imports: Dict[str, Dict[str, str]] = {}
        # Top-level package names of the analyzed sources ("production_
        # stack_tpu", fixture roots): aliases outside these are external.
        self._package_roots: Set[str] = {
            _module_name(src.rel).split(".")[0] for src in sources
        }
        self._index()
        self._infer_attr_types()
        self._build_edges()

    # -- indexing ----------------------------------------------------------

    def _index(self) -> None:
        for src in self.sources:
            mod = _module_name(src.rel)
            imports: Dict[str, str] = {}
            for node in ast.walk(src.tree):
                if isinstance(node, ast.Import):
                    for a in node.names:
                        imports[a.asname or a.name.split(".")[0]] = a.name
                elif isinstance(node, ast.ImportFrom) and node.module:
                    for a in node.names:
                        imports[a.asname or a.name] = f"{node.module}.{a.name}"
            self._imports[mod] = imports

            def add(node: "ast.FunctionDef | ast.AsyncFunctionDef",
                    cls: Optional[str]) -> None:
                q = (
                    f"{mod}:{cls}.{node.name}" if cls else f"{mod}:{node.name}"
                )
                info = FuncInfo(
                    qualname=q, module=mod, cls=cls, name=node.name,
                    node=node, src=src,
                    is_async=isinstance(node, ast.AsyncFunctionDef),
                )
                self.functions[q] = info
                self.by_name.setdefault(node.name, []).append(q)
                if cls:
                    self.by_class.setdefault(cls, {})[node.name] = q

            for node in src.tree.body:
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    add(node, None)
                elif isinstance(node, ast.ClassDef):
                    for sub in node.body:
                        if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                            add(sub, node.name)

    # -- attribute typing --------------------------------------------------

    def _ann_class_name(self, ann: Optional[ast.expr]) -> Optional[str]:
        """Bare class name out of an annotation expression: ``T``,
        ``mod.T``, ``Optional[T]``, or the string forms of any of those.
        Only names that are actually package classes count."""
        name: Optional[str] = None
        if isinstance(ann, ast.Name):
            name = ann.id
        elif isinstance(ann, ast.Attribute):
            name = ann.attr
        elif isinstance(ann, ast.Subscript):
            return self._ann_class_name(ann.slice)
        elif isinstance(ann, ast.Constant) and isinstance(ann.value, str):
            text = ann.value.strip()
            inner = re.fullmatch(r"Optional\[(.+)\]", text)
            if inner:
                text = inner.group(1)
            name = text.rsplit(".", 1)[-1]
            if not name.isidentifier():
                return None
        if name is not None and name in self.by_class:
            return name
        return None

    def _infer_attr_types(self) -> None:
        for info in self.functions.values():
            if info.cls is None:
                continue
            key = (info.module, info.cls)
            types = self.attr_types.setdefault(key, {})
            args = info.node.args
            params: Dict[str, str] = {}
            for a in list(args.args) + list(args.kwonlyargs):
                t = self._ann_class_name(a.annotation)
                if t is not None:
                    params[a.arg] = t
            for node in ast.walk(info.node):
                attr: Optional[str] = None
                t = None
                if isinstance(node, ast.Assign) and len(node.targets) == 1:
                    attr = _self_attr_name(node.targets[0])
                    value: Optional[ast.expr] = node.value
                elif isinstance(node, ast.AnnAssign):
                    attr = _self_attr_name(node.target)
                    t = self._ann_class_name(node.annotation)
                    value = node.value
                else:
                    continue
                if attr is None:
                    continue
                if t is None and isinstance(value, ast.Call):
                    ctor = value.func
                    base = (
                        ctor.id if isinstance(ctor, ast.Name)
                        else ctor.attr if isinstance(ctor, ast.Attribute)
                        else None
                    )
                    if base is not None and base in self.by_class:
                        t = base
                if t is None and isinstance(value, ast.Name):
                    t = params.get(value.id)
                if t is not None:
                    types.setdefault(attr, t)

    # -- edges -------------------------------------------------------------

    def _resolve_call(self, call: ast.Call, info: FuncInfo,
                      ambiguous: bool = True) -> List[str]:
        """Resolve a call to package qualnames.  ``ambiguous=False``
        disables the by-name over-approximation for unknown receivers —
        right for rules where a false edge manufactures a violation out
        of nothing (lock-order cycles), wrong for deny-list reachability
        (where a missed edge is the expensive failure)."""
        fn = call.func
        targets: List[str] = []
        if isinstance(fn, ast.Name):
            name = fn.id
            # Same-module function first.
            q = f"{info.module}:{name}"
            if q in self.functions:
                return [q]
            # from-import of a package function.
            imported = self._imports.get(info.module, {}).get(name)
            if imported:
                dotted_mod, _, attr = imported.rpartition(".")
                q = f"{dotted_mod}:{attr}"
                if q in self.functions:
                    return [q]
            # Class constructor -> __init__.
            init = self.by_class.get(name, {}).get("__init__")
            if init:
                return [init]
            return []
        if not isinstance(fn, ast.Attribute):
            return []
        attr = fn.attr
        base = fn.value
        # self.method() -> same class.
        if isinstance(base, ast.Name) and base.id in ("self", "cls") and info.cls:
            q = self.by_class.get(info.cls, {}).get(attr)
            if q:
                return [q]
            # Fall through: attribute may be a callback or inherited.
        # self.X.method() where self.X's class was inferred from a ctor
        # assignment or an annotated param.
        if (
            isinstance(base, ast.Attribute)
            and isinstance(base.value, ast.Name)
            and base.value.id in ("self", "cls")
            and info.cls is not None
        ):
            t = self.attr_types.get((info.module, info.cls), {}).get(base.attr)
            if t is not None:
                q = self.by_class.get(t, {}).get(attr)
                if q:
                    return [q]
        # param.method() where the enclosing function annotates `param`.
        if isinstance(base, ast.Name):
            args = info.node.args
            for a in list(args.args) + list(args.kwonlyargs):
                if a.arg == base.id:
                    t = self._ann_class_name(a.annotation)
                    if t is not None:
                        q = self.by_class.get(t, {}).get(attr)
                        if q:
                            return [q]
                    break
        # module.func() via import alias.
        if isinstance(base, ast.Name):
            imported = self._imports.get(info.module, {}).get(base.id)
            if imported:
                # Covers both `import pkg.module as m; m.func()` and
                # `from pkg import module; module.func()` — the import
                # table stores the full dotted module either way.
                q = f"{imported}:{attr}"
                if q in self.functions:
                    return [q]
                if imported.split(".")[0] not in self._package_roots:
                    # Known alias of an EXTERNAL module (logging, os.path
                    # ...): definitively not a package call — never fall
                    # through to the by-name guess (`logging.shutdown()`
                    # must not resolve to every package `shutdown`).
                    return targets
        # Unknown receiver: by-name over-approximation.
        if ambiguous:
            candidates = self.by_name.get(attr, [])
            if 0 < len(candidates) <= _MAX_AMBIGUOUS_TARGETS:
                targets.extend(candidates)
        return targets

    def _build_edges(self) -> None:
        for q, info in self.functions.items():
            outs: Set[str] = set()
            typed: Set[str] = set()
            for node in ast.walk(info.node):
                if isinstance(node, ast.Call):
                    outs.update(self._resolve_call(node, info))
                    typed.update(
                        self._resolve_call(node, info, ambiguous=False)
                    )
            outs.discard(q)
            typed.discard(q)
            self.edges[q] = outs
            self.typed_edges[q] = typed

    # -- queries -----------------------------------------------------------

    def expand_suffix_edges(
        self, suffix_edges: Dict[str, List[str]]
    ) -> Dict[str, List[str]]:
        """Expand suffix-keyed dynamic edges (Config.lifecycle_extra_edges
        style: caller suffix -> callee suffixes) into the full-qualname
        form ``reachable`` consumes."""
        out: Dict[str, List[str]] = {}
        for caller_sfx, callees in suffix_edges.items():
            for q in self.functions:
                if q.endswith(caller_sfx):
                    out.setdefault(q, []).extend(
                        t for sfx in callees for t in self.functions
                        if t.endswith(sfx)
                    )
        return out

    def reachable(
        self,
        roots: Iterable[str],
        extra_edges: Optional[Dict[str, List[str]]] = None,
        exclude: Optional[Set[str]] = None,
        strict: bool = False,
    ) -> Dict[str, Tuple[str, ...]]:
        """BFS from ``roots``; returns {qualname: path-from-root} (path
        includes the qualname itself, root first).  ``extra_edges``
        injects callback edges the AST cannot see.  ``exclude`` qualnames
        (boundary annotations: legacy/gated subtrees) are never entered.
        ``strict=True`` walks ``typed_edges`` (no by-name guesses) — for
        analyses where a phantom edge manufactures a violation."""
        extra = extra_edges or {}
        excl = exclude or set()
        edges = self.typed_edges if strict else self.edges
        out: Dict[str, Tuple[str, ...]] = {}
        queue: List[Tuple[str, Tuple[str, ...]]] = [
            (r, (r,)) for r in roots if r in self.functions and r not in excl
        ]
        while queue:
            q, path = queue.pop(0)
            if q in out:
                continue
            out[q] = path
            nxt = set(edges.get(q, ()))
            nxt.update(extra.get(q, ()))
            for callee in sorted(nxt):
                if (
                    callee in self.functions
                    and callee not in out
                    and callee not in excl
                ):
                    queue.append((callee, path + (callee,)))
        return out

    def _annotated_kinds(self, table_name: str,
                         kind_prefix: str) -> Dict[str, str]:
        found: Dict[str, str] = {}
        for q, info in self.functions.items():
            table: Dict[int, str] = getattr(info.src, table_name)
            first = min(
                [info.def_line]
                + [d.lineno for d in info.node.decorator_list]
            )
            for ln in range(first - 2, info.def_line + 1):
                kind = table.get(ln)
                if kind is not None and kind.startswith(kind_prefix):
                    found[q] = kind
                    break
        return found

    def _annotated(self, table_name: str, kind_prefix: str) -> List[str]:
        return sorted(self._annotated_kinds(table_name, kind_prefix))

    def find_roots(self, kind_prefix: str = "") -> List[str]:
        """Functions annotated ``# stackcheck: root=<kind>`` on or
        directly above their def (decorator lines included)."""
        return self._annotated("roots", kind_prefix)

    def find_boundaries(self, kind_prefix: str = "") -> List[str]:
        """Functions annotated ``# stackcheck: boundary=<kind>``: gated
        legacy subtrees the reachability rules must not descend into."""
        return self._annotated("boundaries", kind_prefix)

    def find_thread_roots(self) -> Dict[str, str]:
        """qualname -> thread name for every function annotated
        ``# stackcheck: thread=<name>`` (the entry point — target= — of a
        named OS thread)."""
        return self._annotated_kinds("threads", "")
