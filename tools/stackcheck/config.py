"""stackcheck configuration: what is checked, against what contract.

Everything path-like is relative to ``repo_root`` so the same checker
runs over the live tree (tests/test_stackcheck.py, CI) and over fixture
trees (tests/fixtures/stackcheck/*) by swapping the Config.
"""

from __future__ import annotations

import dataclasses
from pathlib import Path
from typing import Dict, List, Optional, Tuple


# -- SC1: blocking-call deny list -------------------------------------------

# Dotted call prefixes that block the calling thread on I/O or sleep.
BLOCKING_DOTTED_PREFIXES: Tuple[str, ...] = (
    "time.sleep",
    "socket.",
    "requests.",
    "urllib.request.",
    "http.client.",
    "subprocess.run",
    "subprocess.call",
    "subprocess.check_call",
    "subprocess.check_output",
    "shutil.rmtree",
)

# Attribute-call basenames that are high-confidence blocking regardless of
# receiver: raw socket I/O and JAX device-to-host synchronization.
# (`accept`/`connect` are deliberately absent: too many non-socket
# meanings — guided-decoding Guide.accept, breaker connect bookkeeping.
# Server accept loops are covered by reachability through socket.*.)
BLOCKING_ATTR_NAMES: Tuple[str, ...] = (
    "recv",
    "recv_into",
    "recvfrom",
    "sendall",
    "makefile",
    "block_until_ready",
    "device_get",
)

# Package functions that are blocking BY CONTRACT even though their bodies
# may hide the I/O behind helpers the graph cannot fully resolve (the
# kvserver client's public RPC surface).  Qualname suffixes.
BLOCKING_CONTRACT_SUFFIXES: Tuple[str, ...] = (
    "kvserver.client:RemoteKVClient.get_blocks",
    "kvserver.client:RemoteKVClient.put_blocks",
    "kvserver.client:RemoteKVClient.mget_blocks",
    "kvserver.client:RemoteKVClient.mput_blocks",
    "kvserver.client:RemoteKVClient.delete",
    "kvserver.client:RemoteKVClient.stat",
)

# Method basenames distinctive enough to flag inside async defs without
# receiver typing (the kvserver RPC surface minus names that collide
# with stdlib/web idioms like `delete`/`stat`).
ASYNC_CONTRACT_NAMES: Tuple[str, ...] = (
    "get_blocks",
    "put_blocks",
    "mget_blocks",
    "mput_blocks",
)

# -- SC2: determinism --------------------------------------------------------

WALL_CLOCK_CALLS: Tuple[str, ...] = (
    "time.time",
    "time.time_ns",
    "time.monotonic",
    "time.monotonic_ns",
    "time.perf_counter",
    "datetime.now",
    "datetime.utcnow",
    "datetime.today",
    "datetime.datetime.now",
    "datetime.datetime.utcnow",
)

# random-module functions whose call without an explicit seeded generator
# diverges across lockstep replicas.  (jax.random is keyed, so exempt;
# numpy default_rng(seed)/Generator instances are resolved separately.)
UNSEEDED_RANDOM_PREFIXES: Tuple[str, ...] = (
    "random.",
    "np.random.random",
    "np.random.rand",
    "np.random.randint",
    "np.random.choice",
    "np.random.shuffle",
    "numpy.random.random",
    "numpy.random.rand",
    "numpy.random.randint",
    "numpy.random.choice",
    "numpy.random.shuffle",
)

# Thread-timing observation points: querying another thread's progress in
# plan-deciding code makes the plan depend on thread interleaving.
TIMING_QUERY_ATTRS: Tuple[str, ...] = ("empty", "qsize", "get_nowait")

# Calls that are benign SINKS for a wall-clock value: passing a timestamp
# into observability/trace/logging machinery never affects the plan.
BENIGN_SINK_SUBSTRINGS: Tuple[str, ...] = (
    "obs.", "tracer", "add_span", "step_phase", "observe", "record",
    "log", "debug", "info", "warning", "error", "exception", "_observe",
    "note_", "histogram", "append",
)


# -- SC6: lifecycle roots ----------------------------------------------------

# Qualname suffixes of the functions a graceful shutdown runs: every
# thread/socket/executor release site must be reachable from one of
# these (rules_lifecycle.py).
DEFAULT_LIFECYCLE_ROOTS: Tuple[str, ...] = (
    "engine.server.async_engine:AsyncEngine.close",
    "engine.core.engine:LLMEngine.close",
    "utils.registry:ServiceRegistry.close",
)

# Dynamic close edges the AST cannot resolve (generic `close` attribute
# calls are too ambiguous for by-name resolution): caller suffix ->
# callee suffixes.
DEFAULT_LIFECYCLE_EXTRA_EDGES: Dict[str, List[str]] = {
    # AsyncEngine.close() -> self.engine.close() (attr call, untyped),
    # and -> the slice-group liveness monitor's stop/join (the attr is
    # Optional[GroupLivenessMonitor] behind a multi-host gate, so the
    # strict-typed resolver cannot prove the edge).
    "engine.server.async_engine:AsyncEngine.close": [
        "engine.core.engine:LLMEngine.close",
        "engine.parallel.distributed:GroupLivenessMonitor.stop",
    ],
    # LLMEngine.close() walks the KV plane: prefetch fetchers, offload
    # stager writer, deleter thread, export thread, remote client.
    "engine.core.engine:LLMEngine.close": [
        "engine.kv.prefetch:PrefetchManager.shutdown",
        "engine.kv.offload:OffloadStager.shutdown",
        "engine.kv.offload:HostOffloadManager.close",
        "kvserver.client:RemoteKVClient.close",
    ],
}


@dataclasses.dataclass
class DeploymentSurface:
    """One helm template <-> binary pairing for the SC7 contract."""

    template: str                   # repo-relative template path
    argparse_file: str              # the binary's argparse surface
    route_files: Tuple[str, ...] = ()   # files registering HTTP routes
    values_spec: str = ""           # values subtree, e.g. "routerSpec"
    # values subtree whose drainGraceSeconds must thread into
    # --drain-grace-s (None: the binary has no drain contract).
    drain_values_spec: Optional[str] = None


@dataclasses.dataclass(frozen=True)
class RoleContract:
    """The disagg role-pool contract (SC707): the engine template's
    role-labeled Deployments, the router's role-label flag, and the
    values/schema `roles` surface must agree — a mismatched label key
    deploys fine and silently runs the whole fleet fused."""

    engine_template: str
    engine_argparse_file: str
    router_template: str
    router_argparse_file: str
    roles_values_path: str = "servingEngineSpec.roles"
    role_label_flag: str = "--k8s-role-label"
    role_flag: str = "--disagg-role"


DEFAULT_ROLE_CONTRACT = RoleContract(
    engine_template="helm/templates/deployment-engine.yaml",
    engine_argparse_file="production_stack_tpu/engine/server/api_server.py",
    router_template="helm/templates/deployment-router.yaml",
    router_argparse_file="production_stack_tpu/router/parser.py",
)


@dataclasses.dataclass(frozen=True)
class SliceContract:
    """The multi-host pod-group contract (SC709): a mis-grouped slice
    deploys fine and deadlocks at the first collective (or gets
    decapitated by the first voluntary eviction) — exactly the failure
    shape stackcheck exists to catch pre-deploy."""

    engine_template: str
    pdb_template: str
    modelspec_values_path: str = "servingEngineSpec.modelSpec"
    workers_key: str = "tpuNumWorkers"
    chips_key: str = "requestTPU"
    slice_label_key: str = "app.production-stack-tpu/slice-group"


DEFAULT_SLICE_CONTRACT = SliceContract(
    engine_template="helm/templates/deployment-engine.yaml",
    pdb_template="helm/templates/poddisruptionbudget.yaml",
)


DEFAULT_DEPLOYMENT_SURFACES: Tuple[DeploymentSurface, ...] = (
    DeploymentSurface(
        template="helm/templates/deployment-engine.yaml",
        argparse_file="production_stack_tpu/engine/server/api_server.py",
        route_files=("production_stack_tpu/engine/server/api_server.py",),
        values_spec="servingEngineSpec",
        drain_values_spec="servingEngineSpec",
    ),
    DeploymentSurface(
        template="helm/templates/deployment-router.yaml",
        argparse_file="production_stack_tpu/router/parser.py",
        route_files=(
            "production_stack_tpu/router/routers/main_router.py",
            "production_stack_tpu/router/routers/metrics_router.py",
            "production_stack_tpu/router/routers/debug_router.py",
        ),
        values_spec="routerSpec",
        drain_values_spec="routerSpec",
    ),
    DeploymentSurface(
        template="helm/templates/deployment-cache-server.yaml",
        argparse_file="production_stack_tpu/kvserver/server.py",
        route_files=(),            # TCP framing protocol, no HTTP routes
        values_spec="cacheserverSpec",
        drain_values_spec=None,
    ),
)


@dataclasses.dataclass
class Config:
    repo_root: Path
    # Directories (or single files) scanned for source rules.
    package_dirs: Tuple[str, ...] = ("production_stack_tpu",)
    # async-blocking scope (rule SC150): packages whose async defs must
    # not call sync-blocking APIs (the event loop serves every request).
    async_dirs: Tuple[str, ...] = (
        "production_stack_tpu/router",
        "production_stack_tpu/engine/server",
    )
    # Dynamic callback edges the AST cannot see: caller -> callees.
    extra_edges: Dict[str, List[str]] = dataclasses.field(
        default_factory=lambda: dict(DEFAULT_EXTRA_EDGES)
    )
    # SC2 named allow: the PR-5 leader-publish pattern.  Wall-clock
    # evaluation is structurally confined to the lockstep LEADER, whose
    # decision is broadcast as an event batch that followers REPLAY —
    # replicas therefore never evaluate wall clocks independently even
    # though this function does.  (docs/static-analysis.md#leader-publish)
    leader_publish_qualnames: Tuple[str, ...] = (
        "production_stack_tpu.engine.server.async_engine:AsyncEngine._run_loop",
    )
    # -- metrics contract (SC3) -------------------------------------------
    registry_path: str = "production_stack_tpu/obs/metric_registry.py"
    vocabulary_path: str = "production_stack_tpu/router/stats/vocabulary.py"
    fake_engine_path: str = "production_stack_tpu/testing/fake_engine.py"
    dashboard_path: str = "observability/tpu-dashboard.json"
    docs_path: str = "docs/observability.md"
    # -- gate safety (SC4) -------------------------------------------------
    # (config file, class names) whose bool/Optional[bool] fields are gates.
    gate_classes: Tuple[Tuple[str, Tuple[str, ...]], ...] = (
        (
            "production_stack_tpu/engine/config.py",
            ("SchedulerConfig", "CacheConfig", "ObsConfig"),
        ),
    )
    # argparse surfaces checked for gate flag parity and store_true sanity.
    argparse_files: Tuple[str, ...] = (
        "production_stack_tpu/engine/server/api_server.py",
        "production_stack_tpu/router/parser.py",
    )
    # Gate field name -> CLI flag, where kebab-casing isn't mechanical.
    gate_flag_overrides: Dict[str, str] = dataclasses.field(
        default_factory=lambda: {"enable_prefix_caching": "--no-prefix-caching"}
    )
    # -- resource lifecycle (SC6) ------------------------------------------
    lifecycle_roots: Tuple[str, ...] = DEFAULT_LIFECYCLE_ROOTS
    lifecycle_extra_edges: Dict[str, List[str]] = dataclasses.field(
        default_factory=lambda: {
            k: list(v) for k, v in DEFAULT_LIFECYCLE_EXTRA_EDGES.items()
        }
    )
    # -- deployment contract (SC7) -----------------------------------------
    helm_values_path: Optional[str] = "helm/values.yaml"
    helm_schema_path: Optional[str] = "helm/values.schema.json"
    helm_overlay_paths: Tuple[str, ...] = (
        "helm/values-ci.yaml",
        "helm/values-tpu-example.yaml",
        "helm/values-multihost-example.yaml",
    )
    robustness_docs_path: Optional[str] = "docs/robustness.md"
    deployment_surfaces: Tuple[DeploymentSurface, ...] = (
        DEFAULT_DEPLOYMENT_SURFACES
    )
    # SC707 disagg role-pool contract; None disables (fixture trees
    # without a router surface).
    role_contract: Optional[RoleContract] = DEFAULT_ROLE_CONTRACT
    # SC709 multi-host pod-group contract; None disables.
    slice_contract: Optional[SliceContract] = DEFAULT_SLICE_CONTRACT
    # -- SC708: autoscaling PromQL contract --------------------------------
    # YAML surfaces whose tpu:/tpu_router: family references must exist
    # in the metric registry, and whose HPA custom-metric names must be
    # prometheus-adapter `as:` renames — an unregistered family deploys
    # fine and the HPA silently never scales (the SC707 failure shape).
    observability_yaml_paths: Tuple[str, ...] = (
        "observability/prom-adapter.yaml",
        "observability/hpa-example.yaml",
        "observability/kube-prom-stack.yaml",
    )
    hpa_template_paths: Tuple[str, ...] = ("helm/templates/hpa.yaml",)
    prom_adapter_path: Optional[str] = "observability/prom-adapter.yaml"
    baseline_path: str = "tools/stackcheck/baseline.json"

    def resolve(self, rel: Optional[str]) -> Optional[Path]:
        return None if rel is None else self.repo_root / rel


# Scheduler callbacks are wired at engine construction
# (engine/core/engine.py LLMEngine.__init__) and invoked through
# ``self.offload_cb``/``restore_cb``/``remote_prefix_cb`` — invisible to
# static call resolution, but exactly the edges PR 4's invariant is about.
_SCHED = "production_stack_tpu.engine.core.scheduler:Scheduler"
_ENG = "production_stack_tpu.engine.core.engine:LLMEngine"
DEFAULT_EXTRA_EDGES: Dict[str, List[str]] = {
    f"{_SCHED}._preempt_youngest": [f"{_ENG}.offload_seq_blocks"],
    f"{_SCHED}._try_schedule_prefill": [
        f"{_ENG}.restore_seq_blocks",
        f"{_ENG}.fetch_remote_prefix",
    ],
}
