"""stackcheck core: source model, annotations, violations, baseline.

The checker is pure stdlib and NEVER imports the code under analysis —
every fact comes from ``ast`` over the source tree, so it runs in the
lint CI job without jax/aiohttp installed and cannot be confused by
import-time side effects.

Annotation grammar (docs/static-analysis.md):

    # stackcheck: root=step-thread
        On the line(s) directly above a ``def`` (or on the def line):
        marks the function as a reachability ROOT for the blocking (SC1)
        and determinism (SC2) rule families.

    # stackcheck: allow=SC101 reason=<free text to end of line>
        Suppresses the named rule(s) (comma-separated) on the same line,
        the line above the flagged statement, or — when placed on/above a
        ``def`` — for the whole function body.  A reason is mandatory:
        an allow without one is itself a violation (SC001), so every
        suppression records WHY the invariant legitimately bends there.

Baseline (``tools/stackcheck/baseline.json``): the escape hatch for
pre-existing debt.  Keys are ``rule::file::qualname::detail`` (no line
numbers, so unrelated edits don't churn it).  The ratchet is one-way:
``--update-baseline`` refuses to grow any rule's count — debt may only
be paid down or explicitly annotated in source.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import re
from pathlib import Path
from typing import Dict, List, Optional, Set, Tuple

ANNOTATION_RE = re.compile(
    r"#\s*stackcheck:\s*(?P<body>.+?)\s*$"
)
ALLOW_RE = re.compile(
    r"allow=(?P<rules>[A-Z0-9,]+)(?:\s+reason=(?P<reason>.+))?"
)
ROOT_RE = re.compile(r"root=(?P<kind>[a-z-]+)")
BOUNDARY_RE = re.compile(
    r"boundary=(?P<kind>[a-z-]+)(?:\s+reason=(?P<reason>.+))?"
)


@dataclasses.dataclass(frozen=True)
class Violation:
    rule: str       # e.g. "SC101"
    file: str       # repo-relative posix path
    line: int
    qualname: str   # dotted location, e.g. "engine.core.scheduler:Scheduler.schedule"
    message: str
    detail: str = ""  # stable discriminator for the baseline key

    @property
    def key(self) -> str:
        return f"{self.rule}::{self.file}::{self.qualname}::{self.detail}"

    def render(self) -> str:
        return f"{self.file}:{self.line}: {self.rule} [{self.qualname}] {self.message}"


@dataclasses.dataclass
class Allow:
    rules: Tuple[str, ...]
    reason: Optional[str]
    line: int


class SourceFile:
    """One parsed module: AST + per-line annotation maps."""

    def __init__(self, path: Path, rel: str, text: str):
        self.path = path
        self.rel = rel
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text, filename=str(path))
        # line -> Allow entries whose comment sits ON that line.
        self.allows: Dict[int, List[Allow]] = {}
        self.roots: Dict[int, str] = {}  # line -> root kind
        # line -> boundary kind: the annotated function is a legacy/
        # gated subtree the reachability rules must not descend into.
        # A reason is mandatory (same rationale as allow=).
        self.boundaries: Dict[int, str] = {}
        self.bad_annotations: List[int] = []
        for i, raw in enumerate(self.lines, start=1):
            m = ANNOTATION_RE.search(raw)
            if not m:
                continue
            body = m.group("body")
            rm = ROOT_RE.search(body)
            if rm:
                self.roots[i] = rm.group("kind")
                continue
            bm = BOUNDARY_RE.search(body)
            if bm:
                reason = bm.group("reason")
                if not reason or not reason.strip():
                    self.bad_annotations.append(i)
                else:
                    self.boundaries[i] = bm.group("kind")
                continue
            am = ALLOW_RE.search(body)
            if am:
                rules = tuple(
                    r for r in am.group("rules").split(",") if r
                )
                reason = am.group("reason")
                if not rules or not reason or not reason.strip():
                    self.bad_annotations.append(i)
                else:
                    self.allows.setdefault(i, []).append(
                        Allow(rules=rules, reason=reason.strip(), line=i)
                    )
                continue
            # Unrecognized stackcheck directive.
            self.bad_annotations.append(i)

    def allowed_at(self, line: int, rule: str,
                   func_lines: Optional[Tuple[int, int]] = None) -> bool:
        """True when ``rule`` is suppressed at ``line``: an allow on the
        same line, the line directly above, or one covering the whole
        enclosing function (annotation on/above its ``def``)."""
        for ln in (line, line - 1):
            for al in self.allows.get(ln, ()):
                if rule in al.rules or "ALL" in al.rules:
                    return True
        if func_lines is not None:
            def_line, _ = func_lines
            for ln in (def_line, def_line - 1, def_line - 2):
                for al in self.allows.get(ln, ()):
                    if rule in al.rules or "ALL" in al.rules:
                        return True
        return False


def load_sources(root: Path, package_dirs: List[str],
                 exclude: Tuple[str, ...] = ("__pycache__",)) -> List[SourceFile]:
    out: List[SourceFile] = []
    for pkg in package_dirs:
        base = root / pkg
        if base.is_file():
            out.append(SourceFile(base, base.relative_to(root).as_posix(),
                                  base.read_text()))
            continue
        for path in sorted(base.rglob("*.py")):
            if any(part in exclude for part in path.parts):
                continue
            rel = path.relative_to(root).as_posix()
            out.append(SourceFile(path, rel, path.read_text()))
    return out


def annotation_violations(sources: List[SourceFile]) -> List[Violation]:
    out = []
    for src in sources:
        for line in src.bad_annotations:
            out.append(Violation(
                rule="SC001",
                file=src.rel,
                line=line,
                qualname=src.rel,
                message="malformed stackcheck annotation (allow= needs "
                        "comma-separated rule ids AND a reason=...)",
                detail=f"line{line}",
            ))
    return out


# -- baseline ----------------------------------------------------------------

def load_baseline(path: Path) -> Set[str]:
    if not path.exists():
        return set()
    data = json.loads(path.read_text())
    return set(data.get("entries", []))


def write_baseline(path: Path, violations: List[Violation],
                   previous: Set[str]) -> Optional[str]:
    """Write the baseline from the current violation set.  Ratchet: any
    rule whose entry count would GROW vs the previous baseline is an
    error (returns the message; nothing written)."""
    new_entries = sorted({v.key for v in violations})

    def counts(entries) -> Dict[str, int]:
        c: Dict[str, int] = {}
        for e in entries:
            rule = e.split("::", 1)[0]
            c[rule] = c.get(rule, 0) + 1
        return c

    prev_c, new_c = counts(previous), counts(new_entries)
    grew = [
        f"{rule}: {prev_c.get(rule, 0)} -> {n}"
        for rule, n in sorted(new_c.items())
        if n > prev_c.get(rule, 0) and previous
    ]
    if grew:
        return (
            "baseline ratchet: per-rule counts may only decrease "
            "(fix or annotate new violations instead): "
            + "; ".join(grew)
        )
    path.write_text(json.dumps({
        "version": 1,
        "counts": counts(new_entries),
        "entries": new_entries,
    }, indent=2) + "\n")
    return None
