"""stackcheck core: source model, annotations, violations, baseline.

The checker is pure stdlib and NEVER imports the code under analysis —
every fact comes from ``ast`` over the source tree, so it runs in the
lint CI job without jax/aiohttp installed and cannot be confused by
import-time side effects.

Annotation grammar (docs/static-analysis.md):

    # stackcheck: root=step-thread
        On the line(s) directly above a ``def`` (or on the def line):
        marks the function as a reachability ROOT for the blocking (SC1)
        and determinism (SC2) rule families.

    # stackcheck: thread=<name>
        On/above a ``def``: the function is the ENTRY POINT of a named
        OS thread (its target=), e.g. ``thread=kv-prefetch``.  The lock
        rule family (SC5) attributes every function reachable from it to
        that thread when deciding which shared state is touched from
        more than one thread.  ``async def``s are implicitly attributed
        to the ``asyncio-loop`` thread.

    # stackcheck: allow=SC101 reason=<free text to end of line>
        Suppresses the named rule(s) (comma-separated) on the same line,
        the line above the flagged statement, or — when placed on/above a
        ``def`` — for the whole function body.  A reason is mandatory:
        an allow without one is itself a violation (SC001), so every
        suppression records WHY the invariant legitimately bends there.

Baseline (``tools/stackcheck/baseline.json``): the escape hatch for
pre-existing debt.  Keys are ``rule::file::qualname::detail`` (no line
numbers, so unrelated edits don't churn it).  The ratchet is one-way:
``--update-baseline`` refuses to grow any rule's count — debt may only
be paid down or explicitly annotated in source.  Entries for the SC5/
SC6/SC7 families additionally must carry an ``expires`` date (an entry
without one never suppresses), so grandfathered concurrency/lifecycle/
deployment findings cannot live forever.
"""

from __future__ import annotations

import ast
import dataclasses
import datetime as _dt
import json
import re
from pathlib import Path
from typing import Dict, Iterable, Iterator, List, Optional, Set, Tuple

ANNOTATION_RE = re.compile(
    r"#\s*stackcheck:\s*(?P<body>.+?)\s*$"
)
ALLOW_RE = re.compile(
    r"allow=(?P<rules>[A-Z0-9,]+)(?:\s+reason=(?P<reason>.+))?"
)
ROOT_RE = re.compile(r"root=(?P<kind>[a-z-]+)")
THREAD_RE = re.compile(r"thread=(?P<kind>[a-z0-9-]+)")
BOUNDARY_RE = re.compile(
    r"boundary=(?P<kind>[a-z-]+)(?:\s+reason=(?P<reason>.+))?"
)

# Rule-id prefixes whose baseline entries must carry an expiry date
# (the ISSUE-7 families: races, lifecycle, deployment drift).
EXPIRY_REQUIRED_PREFIXES: Tuple[str, ...] = ("SC5", "SC6", "SC7")


def self_attr_name(node: Optional[ast.expr]) -> Optional[str]:
    """``self.X`` / ``cls.X`` receiver expression -> ``"X"``, else None.

    The single definition shared by callgraph attr typing, SC5 lock
    tracking, and SC6 resource tracking — the three must agree on what
    counts as instance state or their attributions silently diverge.
    """
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id in ("self", "cls")
    ):
        return node.attr
    return None


@dataclasses.dataclass(frozen=True)
class Violation:
    rule: str       # e.g. "SC101"
    file: str       # repo-relative posix path
    line: int
    qualname: str   # dotted location, e.g. "engine.core.scheduler:Scheduler.schedule"
    message: str
    detail: str = ""  # stable discriminator for the baseline key

    @property
    def key(self) -> str:
        return f"{self.rule}::{self.file}::{self.qualname}::{self.detail}"

    def render(self) -> str:
        return f"{self.file}:{self.line}: {self.rule} [{self.qualname}] {self.message}"


@dataclasses.dataclass
class Allow:
    rules: Tuple[str, ...]
    reason: Optional[str]
    line: int


class SourceFile:
    """One parsed module: AST + per-line annotation maps."""

    def __init__(self, path: Path, rel: str, text: str) -> None:
        self.path = path
        self.rel = rel
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text, filename=str(path))
        # line -> Allow entries whose comment sits ON that line.
        self.allows: Dict[int, List[Allow]] = {}
        self.roots: Dict[int, str] = {}  # line -> root kind
        self.threads: Dict[int, str] = {}  # line -> thread name
        # line -> boundary kind: the annotated function is a legacy/
        # gated subtree the reachability rules must not descend into.
        # A reason is mandatory (same rationale as allow=).
        self.boundaries: Dict[int, str] = {}
        self.bad_annotations: List[int] = []
        for i, raw in enumerate(self.lines, start=1):
            m = ANNOTATION_RE.search(raw)
            if not m:
                continue
            body = m.group("body")
            rm = ROOT_RE.search(body)
            if rm:
                self.roots[i] = rm.group("kind")
                continue
            tm = THREAD_RE.search(body)
            if tm:
                self.threads[i] = tm.group("kind")
                continue
            bm = BOUNDARY_RE.search(body)
            if bm:
                reason = bm.group("reason")
                if not reason or not reason.strip():
                    self.bad_annotations.append(i)
                else:
                    self.boundaries[i] = bm.group("kind")
                continue
            am = ALLOW_RE.search(body)
            if am:
                rules = tuple(
                    r for r in am.group("rules").split(",") if r
                )
                reason = am.group("reason")
                if not rules or not reason or not reason.strip():
                    self.bad_annotations.append(i)
                else:
                    self.allows.setdefault(i, []).append(
                        Allow(rules=rules, reason=reason.strip(), line=i)
                    )
                continue
            # Unrecognized stackcheck directive.
            self.bad_annotations.append(i)

    def allowed_at(self, line: int, rule: str,
                   func_lines: Optional[Tuple[int, int]] = None) -> bool:
        """True when ``rule`` is suppressed at ``line``: an allow on the
        same line, the line directly above, or one covering the whole
        enclosing function (annotation on/above its ``def``)."""
        for ln in (line, line - 1):
            for al in self.allows.get(ln, ()):
                if rule in al.rules or "ALL" in al.rules:
                    return True
        if func_lines is not None:
            def_line, _ = func_lines
            for ln in (def_line, def_line - 1, def_line - 2):
                for al in self.allows.get(ln, ()):
                    if rule in al.rules or "ALL" in al.rules:
                        return True
        return False


def load_sources(root: Path, package_dirs: List[str],
                 exclude: Tuple[str, ...] = ("__pycache__",)) -> List[SourceFile]:
    out: List[SourceFile] = []
    for pkg in package_dirs:
        base = root / pkg
        if base.is_file():
            out.append(SourceFile(base, base.relative_to(root).as_posix(),
                                  base.read_text()))
            continue
        for path in sorted(base.rglob("*.py")):
            if any(part in exclude for part in path.parts):
                continue
            rel = path.relative_to(root).as_posix()
            out.append(SourceFile(path, rel, path.read_text()))
    return out


def annotation_violations(sources: List[SourceFile]) -> List[Violation]:
    out = []
    for src in sources:
        for line in src.bad_annotations:
            out.append(Violation(
                rule="SC001",
                file=src.rel,
                line=line,
                qualname=src.rel,
                message="malformed stackcheck annotation (allow= needs "
                        "comma-separated rule ids AND a reason=...)",
                detail=f"line{line}",
            ))
    return out


# -- baseline ----------------------------------------------------------------

def _needs_expiry(key: str) -> bool:
    return key.split("::", 1)[0][:3] in EXPIRY_REQUIRED_PREFIXES


@dataclasses.dataclass
class Baseline:
    """Parsed baseline: plain (permanent) entries for the legacy rule
    families, and expiring entries for the SC5/SC6/SC7 families.

    A plain entry for an expiry-required family, or an expiring entry
    past its date, is NOT live — the violation resurfaces."""

    plain: Set[str] = dataclasses.field(default_factory=set)
    # key -> {"expires": "YYYY-MM-DD", "reason": "..."}
    expiring: Dict[str, Dict[str, str]] = dataclasses.field(default_factory=dict)
    today: _dt.date = dataclasses.field(default_factory=_dt.date.today)
    # Memo for live_keys(): every `key in baseline` membership test goes
    # through it, and recomputing would re-parse every expiry date.
    _live: Optional[Set[str]] = dataclasses.field(
        default=None, init=False, repr=False, compare=False,
    )

    def _expired(self, key: str) -> bool:
        meta = self.expiring.get(key)
        if meta is None:
            return False
        try:
            return _dt.date.fromisoformat(meta.get("expires", "")) < self.today
        except ValueError:
            return True  # unparseable expiry never suppresses

    def live_keys(self) -> Set[str]:
        if self._live is None:
            live = {k for k in self.plain if not _needs_expiry(k)}
            live |= {k for k in self.expiring if not self._expired(k)}
            self._live = live
        return self._live

    def invalid_plain(self) -> Set[str]:
        """Plain entries for families that require an expiry date."""
        return {k for k in self.plain if _needs_expiry(k)}

    def expired_keys(self) -> Set[str]:
        return {k for k in self.expiring if self._expired(k)}

    def __contains__(self, key: str) -> bool:
        return key in self.live_keys()

    def __len__(self) -> int:
        return len(self.live_keys())

    def __iter__(self) -> Iterator[str]:
        return iter(sorted(self.live_keys()))


def load_baseline(path: Path,
                  today: Optional[_dt.date] = None) -> Baseline:
    if not path.exists():
        return Baseline(today=today or _dt.date.today())
    data = json.loads(path.read_text())
    plain = set(data.get("entries", []))
    expiring: Dict[str, Dict[str, str]] = {}
    for entry in data.get("expiring", []):
        if isinstance(entry, dict) and "key" in entry:
            expiring[str(entry["key"])] = {
                "expires": str(entry.get("expires", "")),
                "reason": str(entry.get("reason", "")),
            }
    return Baseline(plain=plain, expiring=expiring,
                    today=today or _dt.date.today())


def _rule_counts(keys: Iterable[str]) -> Dict[str, int]:
    c: Dict[str, int] = {}
    for k in keys:
        rule = k.split("::", 1)[0]
        c[rule] = c.get(rule, 0) + 1
    return c


def write_baseline(path: Path, violations: List[Violation],
                   previous: Baseline) -> Optional[str]:
    """Write the baseline from the current violation set.  Ratchet: any
    rule whose entry count would GROW vs the previous baseline is an
    error (returns the message; nothing written).  SC5/SC6/SC7 keys can
    only be (re)written when the previous baseline already carries an
    expiring entry for them — new findings in those families are fixed
    or annotated in source, never auto-grandfathered."""
    keys = sorted({v.key for v in violations})

    prev_live = previous.live_keys()
    # `not in prev_live` (not merely `not in previous.expiring`): an
    # EXPIRED expiring entry must not be silently re-written with its
    # stale date — the next plain run would still fail, contradicting
    # the "baseline written" success.
    unexpirable = [
        k for k in keys
        if _needs_expiry(k) and k not in prev_live
    ]
    if unexpirable:
        return (
            "SC5/SC6/SC7 findings cannot be auto-baselined: they need an "
            "explicit `expiring` entry (key + expires + reason) added — "
            "or, if expired, renewed — by hand, or a fix/annotation in "
            "source: "
            + "; ".join(unexpirable[:5])
            + ("; ..." if len(unexpirable) > 5 else "")
        )
    prev_c, new_c = _rule_counts(prev_live), _rule_counts(keys)
    grew = [
        f"{rule}: {prev_c.get(rule, 0)} -> {n}"
        for rule, n in sorted(new_c.items())
        if n > prev_c.get(rule, 0) and prev_live
    ]
    if grew:
        return (
            "baseline ratchet: per-rule counts may only decrease "
            "(fix or annotate new violations instead): "
            + "; ".join(grew)
        )

    plain = [k for k in keys if not _needs_expiry(k)]
    expiring = [
        {"key": k, **previous.expiring[k]}
        for k in keys if _needs_expiry(k)
    ]
    payload: Dict[str, object] = {
        "version": 2,
        "counts": _rule_counts(keys),
        "entries": plain,
    }
    if expiring:
        payload["expiring"] = expiring
    path.write_text(json.dumps(payload, indent=2) + "\n")
    return None
