"""Rule family SC6 — resource lifecycle.

Invariant (PR 5/6, CHANGES.md): *a graceful drain releases everything.*
Every thread, socket, executor, and pool the package creates must have a
join/close/shutdown site reachable from the engine's close path
(``LLMEngine.close()`` / ``AsyncEngine.close()``) or the registry sweep
the router's drain runs (``ServiceRegistry.close``).  PR 6's
deleter-flush bug — a drain dropping queued remote DELs because nothing
on the close path waited for the deleter thread — is exactly the class
of leak this family catches statically.

SC601  ``threading.Thread`` created with no join/release site reachable
       from a lifecycle root.  Daemon threads are NOT exempt: dying with
       the process means dropping whatever they still held (queued DELs,
       staged KV snapshots); a daemon thread that is genuinely safe to
       abandon carries an ``allow=SC601 reason=...`` saying why.
SC602  socket created and stored on ``self`` with no ``.close()`` path
       reachable from a lifecycle root, or created locally and neither
       closed, returned (ownership transfer), nor used via ``with``.
SC603  executor/pool (ThreadPoolExecutor, ProcessPoolExecutor,
       multiprocessing.Pool) with no ``shutdown``/``close``/
       ``terminate`` site reachable from a lifecycle root.

Release-site matching is attribute-based: a resource stored to
``self.X`` is released by any reference to ``self.X.join`` / ``.close``
/ ``.shutdown`` (call or bare reference — ``asyncio.to_thread(
self._thread.join, 30)`` counts), by ``for t in self.X: t.join()`` for
resource lists, or through a local aliased from the attribute — the
swap-under-lock close idiom ``t, self.X = self.X, None`` followed by
``t.join()`` (also ``ts, self.X = self.X, []`` + ``for t in ts:
t.join()``), which confines the handle mutation to the lock without
joining under it.  The method containing the release must be reachable
from a lifecycle root (``Config.lifecycle_roots`` + the declared
``lifecycle_extra_edges`` for dynamic hookups like registry closables).
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Set, Tuple

from tools.stackcheck import config as C
from tools.stackcheck.callgraph import CallGraph, FuncInfo
from tools.stackcheck.core import Violation
from tools.stackcheck.core import self_attr_name as _self_attr
from tools.stackcheck.rules_blocking import dotted_name

_RELEASE_NAMES = (
    "join", "close", "shutdown", "terminate", "stop", "cancel",
)

_THREAD_CTORS = ("threading.Thread", "Thread")
_SOCKET_CTORS = (
    "socket.socket", "socket.create_connection", "socket.socketpair",
)
_POOL_CTORS = (
    "ThreadPoolExecutor", "ProcessPoolExecutor",
    "concurrent.futures.ThreadPoolExecutor",
    "concurrent.futures.ProcessPoolExecutor",
    "multiprocessing.Pool", "mp.Pool",
)


@dataclasses.dataclass
class ResourceSite:
    kind: str          # "thread" | "socket" | "pool"
    rule: str          # SC601 | SC602 | SC603
    ctor: str          # rendered constructor name
    line: int
    func: str          # qualname of the creating function
    attr: Optional[str]   # self.<attr> it is stored to (None = local)
    daemon: bool = False


def _store_target(parents: Dict[int, ast.AST],
                  node: ast.Call) -> Optional[ast.expr]:
    """The assignment target the call's value flows into, if any
    (direct assign only — x = ctor(...) / self.x = ctor(...))."""
    parent = parents.get(id(node))
    if isinstance(parent, ast.Assign) and len(parent.targets) == 1:
        return parent.targets[0]
    if isinstance(parent, ast.AnnAssign):
        return parent.target
    return None


def _classify(call: ast.Call) -> Optional[Tuple[str, str]]:
    name = dotted_name(call.func)
    base = name.rsplit(".", 1)[-1]
    if name in _THREAD_CTORS:
        return ("thread", "SC601")
    if name in _SOCKET_CTORS:
        return ("socket", "SC602")
    if name in _POOL_CTORS or base in (
        "ThreadPoolExecutor", "ProcessPoolExecutor"
    ):
        return ("pool", "SC603")
    return None


def _is_daemon(call: ast.Call) -> bool:
    for kw in call.keywords:
        if kw.arg == "daemon" and isinstance(kw.value, ast.Constant):
            return bool(kw.value.value)
    return False


def collect_resources(graph: CallGraph) -> List[Tuple[FuncInfo, ResourceSite]]:
    out: List[Tuple[FuncInfo, ResourceSite]] = []
    for q, info in graph.functions.items():
        parents: Dict[int, ast.AST] = {}
        returned: Set[int] = set()
        with_items: Set[int] = set()
        appended_attr: Dict[int, str] = {}
        # Local names that escape ownership or are released in-function:
        returned_locals: Set[str] = set()
        released_locals: Set[str] = set()
        local_appended_to: Dict[str, str] = {}  # local -> self attr
        for node in ast.walk(info.node):
            for child in ast.iter_child_nodes(node):
                parents[id(child)] = node
            if isinstance(node, ast.Return) and node.value is not None:
                for sub in ast.walk(node.value):
                    returned.add(id(sub))
                    if isinstance(sub, ast.Name):
                        returned_locals.add(sub.id)
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    for sub in ast.walk(item.context_expr):
                        with_items.add(id(sub))
            if (
                isinstance(node, ast.Attribute)
                and node.attr in _RELEASE_NAMES
                and isinstance(node.value, ast.Name)
            ):
                released_locals.add(node.value.id)
            # self.X.append(ctor(...)) / self.X.append(local) store into
            # a resource list owned by the instance.
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "append"
            ):
                attr = _self_attr(node.func.value)
                if attr is not None:
                    for arg in node.args:
                        for sub in ast.walk(arg):
                            appended_attr[id(sub)] = attr
                        if isinstance(arg, ast.Name):
                            local_appended_to[arg.id] = attr
        for node in ast.walk(info.node):
            if not isinstance(node, ast.Call):
                continue
            cls_rule = _classify(node)
            if cls_rule is None:
                continue
            kind, rule = cls_rule
            if id(node) in returned or id(node) in with_items:
                continue  # ownership transferred / scoped release
            target = _store_target(parents, node)
            attr = _self_attr(target)
            if attr is None and id(node) in appended_attr:
                attr = appended_attr[id(node)]
            if attr is None and isinstance(target, ast.Name):
                local = target.id
                if local in local_appended_to:
                    # `t = ctor(...)` then `self.X.append(t)`: the
                    # instance list owns it — judge it as self.X.
                    attr = local_appended_to[local]
                elif local in returned_locals:
                    continue  # ownership transferred to the caller
                elif local in released_locals:
                    continue  # released on the same local name here
            out.append((info, ResourceSite(
                kind=kind, rule=rule, ctor=dotted_name(node.func),
                line=node.lineno, func=q, attr=attr,
                daemon=_is_daemon(node) if kind == "thread" else False,
            )))
    return out


def _release_sites(graph: CallGraph, module: str, cls: Optional[str],
                   attr: str) -> Set[str]:
    """Qualnames of functions in the same class referencing a release
    method on self.<attr> — directly, on elements iterated from it, or
    through a local aliased from it (the swap-under-lock close idiom:
    ``t, self.X = self.X, None`` followed by ``t.join()``)."""
    out: Set[str] = set()
    for q, info in graph.functions.items():
        if info.module != module or info.cls != cls:
            continue
        aliases: Set[str] = set()
        for node in ast.walk(info.node):
            if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
                continue
            tgt, val = node.targets[0], node.value
            if (
                isinstance(tgt, ast.Tuple)
                and isinstance(val, ast.Tuple)
                and len(tgt.elts) == len(val.elts)
            ):
                pairs = list(zip(tgt.elts, val.elts))
            else:
                pairs = [(tgt, val)]
            for t, v in pairs:
                if isinstance(t, ast.Name) and _self_attr(v) == attr:
                    aliases.add(t.id)
        loop_vars: Set[str] = set()
        for node in ast.walk(info.node):
            if (
                isinstance(node, ast.For)
                and isinstance(node.target, ast.Name)
                and (
                    _self_attr(node.iter) == attr
                    or (
                        isinstance(node.iter, ast.Name)
                        and node.iter.id in aliases
                    )
                )
            ):
                loop_vars.add(node.target.id)
        for node in ast.walk(info.node):
            if not isinstance(node, ast.Attribute):
                continue
            if node.attr not in _RELEASE_NAMES:
                continue
            if _self_attr(node.value) == attr:
                out.add(q)
            elif (
                isinstance(node.value, ast.Name)
                and node.value.id in loop_vars | aliases
            ):
                out.add(q)
    return out


def lifecycle_reachable(graph: CallGraph, cfg: C.Config) -> Set[str]:
    roots = [
        q for q in graph.functions
        if any(q.endswith(sfx) for sfx in cfg.lifecycle_roots)
    ]
    extra = graph.expand_suffix_edges(cfg.lifecycle_extra_edges)
    return set(graph.reachable(roots, extra_edges=extra))


def check_lifecycle(graph: CallGraph, cfg: C.Config) -> List[Violation]:
    out: List[Violation] = []
    reachable = lifecycle_reachable(graph, cfg)
    for info, site in collect_resources(graph):
        func_span = (info.def_line, info.end_line)
        if info.src.allowed_at(site.line, site.rule, func_span):
            continue
        released_from: Set[str] = set()
        if site.attr is not None:
            released_from = _release_sites(
                graph, info.module, info.cls, site.attr
            )
        live_release = released_from & reachable
        if live_release:
            continue
        where = (
            f"self.{site.attr}" if site.attr is not None
            else "an unbound local"
        )
        if released_from:
            problem = (
                f"release site(s) {sorted(x.split(':', 1)[-1] for x in released_from)} "
                "exist but none is reachable from a lifecycle root "
                f"({', '.join(s.split(':', 1)[-1] for s in cfg.lifecycle_roots)})"
            )
        else:
            problem = "no join/close/shutdown site exists at all"
        daemon_note = (
            " (daemon=True does not exempt it: dying with the process "
            "drops whatever it still holds — annotate allow=SC601 with "
            "the reason if abandoning it is genuinely safe)"
            if site.daemon else ""
        )
        out.append(Violation(
            rule=site.rule, file=info.src.rel, line=site.line,
            qualname=site.func.split(":", 1)[-1],
            message=(
                f"{site.kind} `{site.ctor}` stored in {where}: {problem}"
                f"{daemon_note}"
            ),
            detail=f"{site.attr or 'local'}:{site.ctor}",
        ))
    return out
