"""CLI: ``python -m tools.stackcheck [options]``.

Exit status: 0 = clean (or every violation baselined), 1 = new
violations (or a baseline-ratchet refusal).  Run from the repo root;
``--root`` points elsewhere for fixture trees.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path
from typing import List, Optional

from tools.stackcheck import (
    RULE_FAMILIES,
    Config,
    apply_baseline,
    resolve_families,
    run_checks,
    update_baseline,
)
from tools.stackcheck.core import load_baseline


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.stackcheck",
        description="AST/call-graph invariant checker (docs/static-analysis.md)",
    )
    parser.add_argument(
        "--root", default=".",
        help="repo root to analyze (default: cwd)",
    )
    parser.add_argument(
        "--rules", default=None,
        help=f"comma-separated rule families (default: all of "
             f"{','.join(RULE_FAMILIES)}; SC1..SC7 shorthands accepted, "
             "e.g. --rules SC5,SC6,SC7)",
    )
    parser.add_argument(
        "--baseline", default=None,
        help="baseline JSON path (default: tools/stackcheck/baseline.json "
             "under --root when present)",
    )
    parser.add_argument(
        "--update-baseline", action="store_true",
        help="rewrite the baseline from current violations; refuses to "
             "GROW any rule's count (the ratchet)",
    )
    parser.add_argument(
        "--json", action="store_true", dest="as_json",
        help="machine-readable output",
    )
    args = parser.parse_args(argv)

    root = Path(args.root).resolve()
    cfg = Config(repo_root=root)
    families = args.rules.split(",") if args.rules else None
    if families:
        try:
            families = resolve_families(families)
        except ValueError as exc:
            parser.error(str(exc))

    violations = run_checks(cfg, families)

    baseline_path = (
        Path(args.baseline) if args.baseline else root / cfg.baseline_path
    )
    if args.update_baseline:
        err = update_baseline(violations, baseline_path)
        if err:
            print(f"stackcheck: {err}", file=sys.stderr)
            return 1
        print(f"stackcheck: baseline written to {baseline_path} "
              f"({len(violations)} entries)")
        return 0

    baseline = load_baseline(baseline_path)
    split = apply_baseline(violations, baseline)
    new, old = split["new"], split["baselined"]
    for key in sorted(baseline.invalid_plain()):
        print(
            f"stackcheck: baseline entry {key} belongs to an "
            "expiry-required family (SC5/SC6/SC7) but has no `expiring` "
            "metadata — it does NOT suppress", file=sys.stderr,
        )
    for key in sorted(baseline.expired_keys()):
        meta = baseline.expiring[key]
        print(
            f"stackcheck: baseline entry {key} expired on "
            f"{meta.get('expires')} — the finding resurfaces below",
            file=sys.stderr,
        )

    if args.as_json:
        print(json.dumps({
            "new": [vars(v) for v in new],
            "baselined": [vars(v) for v in old],
        }, indent=2))
    else:
        for v in new:
            print(v.render())
        if old:
            print(f"stackcheck: {len(old)} baselined violation(s) "
                  "suppressed (pay the debt down: tools/stackcheck/"
                  "baseline.json)")
    if new:
        print(
            f"stackcheck: {len(new)} new violation(s).  Fix them, or "
            "annotate intentional ones with "
            "`# stackcheck: allow=<rule> reason=...` "
            "(docs/static-analysis.md)",
            file=sys.stderr,
        )
        return 1
    print(f"stackcheck: clean ({len(violations)} total, "
          f"{len(old)} baselined)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
