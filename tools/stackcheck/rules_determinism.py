"""Rule family SC2 — lockstep determinism.

Invariant (PRs 3/5, CHANGES.md): *lockstep replicas never evaluate wall
clocks.*  Under multi-host SPMD every replica must produce the byte-
identical sequence of jitted launches; a plan decision keyed on a wall
clock (or unseeded randomness, or another thread's progress) diverges
replicas and wedges the group in mismatched collectives.

SC201  wall-clock read whose value feeds a BRANCH or a scheduler/plan
       call in code reachable from scheduler/step roots.  Reads that
       only flow into observability sinks (span/histogram/log calls)
       are fine — metrics may disagree across replicas, plans may not.
SC202  unseeded randomness (random.*, np.random module functions)
       reachable from scheduler/step roots.  jax.random is keyed and
       np.random.default_rng(seed)/Generator instances are exempt.
SC203  thread-progress query (.empty()/.qsize()/.get_nowait()) in
       reachable code — the plan would depend on worker-thread timing.

The one sanctioned exception is the *leader-publish* pattern
(cfg.leader_publish_qualnames): the lockstep LEADER evaluates the clock
(deadline sweep, idle heartbeat) and publishes the resulting event batch;
followers replay it verbatim.  Replicas still never *independently*
evaluate wall clocks — the decision is made once and broadcast.
"""

from __future__ import annotations

import ast
from typing import List, Set

from tools.stackcheck import config as C
from tools.stackcheck.callgraph import CallGraph
from tools.stackcheck.core import Violation
from tools.stackcheck.rules_blocking import dotted_name


def _is_wall_clock(call: ast.Call) -> bool:
    name = dotted_name(call.func)
    if name in C.WALL_CLOCK_CALLS:
        # datetime.now(tz) with an argument is still a wall clock read.
        return True
    return False


def _is_unseeded_random(call: ast.Call) -> bool:
    name = dotted_name(call.func)
    for prefix in C.UNSEEDED_RANDOM_PREFIXES:
        if name == prefix.rstrip(".") or name.startswith(prefix):
            return True
    return False


def _is_benign_sink(call: ast.Call) -> bool:
    name = dotted_name(call.func).lower()
    return any(s in name for s in C.BENIGN_SINK_SUBSTRINGS)


class _ClockTaint(ast.NodeVisitor):
    """Intra-function taint: which local names hold wall-clock-derived
    values, and does any tainted value reach a branch condition, a
    comparison, or a non-sink call argument that is a plan/scheduler
    call?  Deliberately shallow (no attribute or inter-procedural
    tracking): the step loop stamps clocks into attributes for metrics
    constantly, and chasing those would drown the signal.  The rule's
    teeth come from the branch/comparison check, which is where a clock
    becomes a *decision*."""

    def __init__(self) -> None:
        self.tainted: Set[str] = set()
        self.flagged: List[ast.AST] = []

    # -- taint sources / propagation ------------------------------------

    def _expr_tainted(self, node: ast.AST) -> bool:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call) and _is_wall_clock(sub):
                return True
            if isinstance(sub, ast.Name) and sub.id in self.tainted:
                return True
        return False

    def visit_Assign(self, node: ast.Assign) -> None:
        if self._expr_tainted(node.value):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    self.tainted.add(tgt.id)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        if self._expr_tainted(node.value) and isinstance(node.target, ast.Name):
            self.tainted.add(node.target.id)
        self.generic_visit(node)

    # -- decision sinks --------------------------------------------------

    def _check_condition(self, test: ast.AST) -> None:
        if self._expr_tainted(test):
            self.flagged.append(test)

    def visit_If(self, node: ast.If) -> None:
        self._check_condition(node.test)
        self.generic_visit(node)

    def visit_While(self, node: ast.While) -> None:
        self._check_condition(node.test)
        self.generic_visit(node)

    def visit_IfExp(self, node: ast.IfExp) -> None:
        self._check_condition(node.test)
        self.generic_visit(node)

    def visit_Assert(self, node: ast.Assert) -> None:
        self._check_condition(node.test)
        self.generic_visit(node)

    def visit_comprehension(self, node: ast.comprehension) -> None:
        for cond in node.ifs:
            self._check_condition(cond)
        self.generic_visit(node)

    def visit_Compare(self, node: ast.Compare) -> None:
        # A comparison on a clock value is a decision even outside an
        # `if` (sorted keys, filters, min/max selection).
        if self._expr_tainted(node):
            self.flagged.append(node)
        # Don't recurse: the If visitor already flagged enclosing tests;
        # flagging both would double-report.

    def visit_Call(self, node: ast.Call) -> None:
        # A tainted value handed to a non-sink call is a decision input
        # escaping this function (e.g. scheduler.set_deadline(now + b)).
        # Sinks (spans/histograms/logs) are fine; args containing a
        # comparison are left to visit_Compare to avoid double-reports.
        if not _is_benign_sink(node) and not _is_wall_clock(node):
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                if any(isinstance(s, ast.Compare) for s in ast.walk(arg)):
                    continue
                if self._expr_tainted(arg):
                    self.flagged.append(node)
                    break
        self.generic_visit(node)

    def run(self, func_node: ast.AST) -> List[ast.AST]:
        # Two passes so taint assigned below its first decision use in
        # source order (loops) still propagates.
        for _ in range(2):
            self.flagged = []
            self.visit(func_node)
        # De-duplicate by location.
        seen = set()
        uniq = []
        for n in self.flagged:
            key = (getattr(n, "lineno", 0), getattr(n, "col_offset", 0))
            if key not in seen:
                seen.add(key)
                uniq.append(n)
        return uniq


def check_determinism(graph: CallGraph, cfg: C.Config) -> List[Violation]:
    out: List[Violation] = []
    roots = graph.find_roots("step")
    reach = graph.reachable(
        roots,
        extra_edges=cfg.extra_edges,
        exclude=set(graph.find_boundaries("step")),
    )
    leader_ok = set(cfg.leader_publish_qualnames)
    for q in reach:
        info = graph.functions[q]
        func_span = (info.def_line, info.end_line)
        where = q.split(":", 1)[-1]

        if q not in leader_ok:
            taint = _ClockTaint()
            for node in taint.run(info.node):
                line = getattr(node, "lineno", info.def_line)
                if info.src.allowed_at(line, "SC201", func_span):
                    continue
                out.append(Violation(
                    rule="SC201", file=info.src.rel, line=line,
                    qualname=where,
                    message=(
                        "wall-clock value feeds a decision in scheduler/"
                        "step-reachable code (lockstep replicas would "
                        "diverge); publish the decision from the leader "
                        "or key it on deterministic state"
                    ),
                    # Baseline keys must stay line-number-free (core.py);
                    # the flagged expression's own source is the stable
                    # discriminator between multiple hits in one function.
                    detail=ast.unparse(node)[:80],
                ))

        for node in ast.walk(info.node):
            if not isinstance(node, ast.Call):
                continue
            if _is_unseeded_random(node):
                if info.src.allowed_at(node.lineno, "SC202", func_span):
                    continue
                out.append(Violation(
                    rule="SC202", file=info.src.rel, line=node.lineno,
                    qualname=where,
                    message=(
                        f"unseeded randomness `{dotted_name(node.func)}` "
                        "in scheduler/step-reachable code"
                    ),
                    detail=dotted_name(node.func),
                ))
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in C.TIMING_QUERY_ATTRS
                and q not in leader_ok
            ):
                if info.src.allowed_at(node.lineno, "SC203", func_span):
                    continue
                out.append(Violation(
                    rule="SC203", file=info.src.rel, line=node.lineno,
                    qualname=where,
                    message=(
                        f"thread-progress query `{dotted_name(node.func)}()` "
                        "in scheduler/step-reachable code (plan would depend "
                        "on worker-thread timing)"
                    ),
                    detail=dotted_name(node.func),
                ))
    return out
