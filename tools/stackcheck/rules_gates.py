"""Rule family SC4 — gate safety.

Invariant (PR 5, CHANGES.md): *every gate is default-off-safe.*  A new
behavior ships behind a gate whose default is ``False`` or ``None``
(= auto, resolved to a safe value); rolling back is always "stop passing
the flag".  And every gate must be REACHABLE from the CLI: a config
field with no ``--X``/``--no-X`` argparse counterpart can't be turned
off in production without a code change — which is how a "default-safe"
gate quietly becomes mandatory.

SC401  bool/Optional[bool] gate field whose default is True (annotate
       with a reason when the always-on default is the established
       contract, e.g. enable_prefix_caching).
SC402  gate field with no matching argparse flag on the engine server
       surface (``--<kebab>``, ``--no-<kebab>``, or a declared override).
SC403  argparse ``store_true`` flag declared with ``default=True`` —
       the flag can then never express False.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Tuple

from tools.stackcheck import config as C
from tools.stackcheck.core import SourceFile, Violation


def _is_bool_annotation(node: Optional[ast.AST]) -> bool:
    if node is None:
        return False
    if isinstance(node, ast.Name):
        return node.id == "bool"
    if isinstance(node, ast.Subscript):  # Optional[bool]
        base = node.value
        if isinstance(base, ast.Name) and base.id == "Optional":
            inner = node.slice
            return isinstance(inner, ast.Name) and inner.id == "bool"
    return False


def _gate_fields(src: SourceFile,
                 classes: Tuple[str, ...],
                 ) -> Iterator[Tuple[str, str, object, int]]:
    """Yield (class, field, default, line) for bool-ish dataclass fields."""
    for node in src.tree.body:
        if not isinstance(node, ast.ClassDef) or node.name not in classes:
            continue
        for stmt in node.body:
            if not isinstance(stmt, ast.AnnAssign):
                continue
            if not isinstance(stmt.target, ast.Name):
                continue
            if not _is_bool_annotation(stmt.annotation):
                continue
            default: object = ...
            if stmt.value is not None:
                try:
                    default = ast.literal_eval(stmt.value)
                except (ValueError, SyntaxError):
                    default = ...
            yield node.name, stmt.target.id, default, stmt.lineno


def _argparse_flags(src: SourceFile) -> Dict[str, Dict[str, object]]:
    """flag string -> {line, store_true, default} from add_argument calls."""
    out: Dict[str, Dict[str, object]] = {}
    for node in ast.walk(src.tree):
        if not isinstance(node, ast.Call):
            continue
        if not (
            isinstance(node.func, ast.Attribute)
            and node.func.attr == "add_argument"
        ):
            continue
        flags = [
            a.value for a in node.args
            if isinstance(a, ast.Constant) and isinstance(a.value, str)
            and a.value.startswith("--")
        ]
        if not flags:
            continue
        kw = {}
        for k in node.keywords:
            if k.arg in ("action", "default", "choices"):
                try:
                    kw[k.arg] = ast.literal_eval(k.value)
                except (ValueError, SyntaxError):
                    kw[k.arg] = ...
        info = {
            "line": node.lineno,
            "store_true": kw.get("action") == "store_true",
            "default": kw.get("default", None),
            "choices": kw.get("choices", None),
        }
        for f in flags:
            out[f] = info
    return out


def check_gates(sources: List[SourceFile], cfg: C.Config) -> List[Violation]:
    out: List[Violation] = []
    by_rel = {s.rel: s for s in sources}

    all_flags: Dict[str, Dict[str, object]] = {}
    for rel in cfg.argparse_files:
        src = by_rel.get(rel)
        if src is None:
            continue
        flags = _argparse_flags(src)
        all_flags.update(flags)
        for flag, info in sorted(flags.items()):
            if info["store_true"] and info["default"] is True:
                if src.allowed_at(info["line"], "SC403"):
                    continue
                out.append(Violation(
                    rule="SC403", file=rel, line=info["line"],
                    qualname="argparse",
                    message=(
                        f"store_true flag {flag} declared with default=True "
                        "can never express False"
                    ),
                    detail=flag,
                ))

    for conf_rel, classes in cfg.gate_classes:
        src = by_rel.get(conf_rel)
        if src is None:
            continue
        for cls, field, default, line in _gate_fields(src, classes):
            qual = f"{cls}.{field}"
            if default is True:
                if not src.allowed_at(line, "SC401"):
                    out.append(Violation(
                        rule="SC401", file=conf_rel, line=line,
                        qualname=qual,
                        message=(
                            f"gate {qual} defaults to True — gates must be "
                            "default-off (False) or auto-safe (None); if "
                            "always-on IS the established contract, "
                            "annotate with the reason"
                        ),
                        detail=field,
                    ))
            kebab = field.replace("_", "-")
            candidates = {
                f"--{kebab}",
                f"--no-{kebab}",
                cfg.gate_flag_overrides.get(field, ""),
            }
            if field.startswith("enable_"):
                stem = field[len("enable_"):].replace("_", "-")
                candidates.update({f"--{stem}", f"--no-{stem}"})
            if not candidates & set(all_flags):
                if src.allowed_at(line, "SC402"):
                    continue
                out.append(Violation(
                    rule="SC402", file=conf_rel, line=line, qualname=qual,
                    message=(
                        f"gate {qual} has no CLI flag parity "
                        f"(expected --{kebab} or --no-{kebab} on the "
                        "argparse surface); an unreachable gate becomes "
                        "mandatory in production"
                    ),
                    detail=field,
                ))
    return out
