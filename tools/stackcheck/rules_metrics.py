"""Rule family SC3 — the three-way metrics contract.

``production_stack_tpu/obs/metric_registry.py`` is the single source of
truth for every ``tpu:``/``tpu_router:`` family (SURVEY §4: the stats
plane is the backbone — scraper, dashboard, HPA rule and fake engine all
key off these names, and a silent rename desyncs them without any test
failing).  stackcheck cross-checks FOUR surfaces against it, in both
directions:

  emit sites    string literals in production_stack_tpu/** (fake engine
                excluded — it is a mirror, not an emitter)
  fake engine   testing/fake_engine.py must mirror every engine family
                flagged ``fake_engine`` (vocabulary constants and the
                EngineObs histogram render path are expanded)
  dashboard     observability/tpu-dashboard.json panel exprs
  docs          the docs/observability.md tables

SC301  emitted family missing from the registry (orphan emit)
SC302  registry family with no emit site (dead entry / rename drift)
SC303  engine family flagged fake_engine not mirrored by the fake
SC304  family flagged dashboard absent from every panel expr
SC305  dashboard expr references a family the registry doesn't know
SC306  family flagged docs absent from docs/observability.md
SC307  docs reference a family the registry doesn't know

prometheus_client quirk handled here: a ``Counter("x")`` is EXPOSED as
``x_total`` — the registry stores exposition names, and emit-site
scanning lifts literals declared inside ``Counter(...)`` accordingly.
"""

from __future__ import annotations

import ast
import json
import re
from pathlib import Path
from typing import Any, Dict, List, Set, Tuple, cast

from tools.stackcheck import config as C
from tools.stackcheck.core import SourceFile, Violation

FAMILY_RE = re.compile(r"\btpu(?:_router)?:[a-z0-9_]+\b")
HIST_SUFFIXES = ("_bucket", "_sum", "_count")
# Docs prose writes families in shell-brace shorthand
# (tpu:step_{schedule,dispatch}_seconds) and glob shorthand
# (tpu:step_*_seconds); expand the former, drop the latter.
_BRACE_RE = re.compile(r"\{([a-z0-9_,]+)\}")


def _prose_families(text: str) -> Set[str]:
    """Family names mentioned in prose/markdown: brace templates are
    expanded, glob templates (name immediately followed by ``*``/``<``)
    are ignored rather than matched as a truncated family."""
    names: Set[str] = set()
    for line in text.splitlines():
        for m in _BRACE_RE.finditer(line):
            if "," not in m.group(1):
                continue  # {server} is a label selector, not alternatives
            prefix = line[: m.start()]
            suffix = line[m.end():]
            pm = re.search(r"tpu(?:_router)?:[a-z0-9_]*_$", prefix)
            sm = re.match(r"[a-z0-9_]*", suffix)
            if pm:
                for alt in m.group(1).split(","):
                    names.add(pm.group(0) + alt + (sm.group(0) if sm else ""))
        for m in FAMILY_RE.finditer(line):
            nxt = line[m.end(): m.end() + 1]
            if nxt in ("*", "<") or m.group(0).endswith("_"):
                continue
            names.add(m.group(0))
    return names


def parse_registry(path: Path) -> Dict[str, Dict[str, object]]:
    """AST-parse the REGISTRY literal (never imports the package)."""
    tree = ast.parse(path.read_text(), filename=str(path))
    for node in tree.body:
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name) and tgt.id == "REGISTRY":
                    return cast(
                        Dict[str, Dict[str, object]],
                        ast.literal_eval(node.value),
                    )
    raise ValueError(f"no REGISTRY assignment found in {path}")


def _vocabulary_constants(path: Path) -> Tuple[Dict[str, str], Dict[str, Set[str]]]:
    """vocabulary.py NAME = "tpu:..." constants and NAME = {..} dicts
    (dict name -> set of family values)."""
    consts: Dict[str, str] = {}
    dicts: Dict[str, Set[str]] = {}
    tree = ast.parse(path.read_text(), filename=str(path))
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        tgt = node.targets[0]
        if not isinstance(tgt, ast.Name):
            continue
        try:
            value = ast.literal_eval(node.value)
        except (ValueError, SyntaxError):
            continue
        if isinstance(value, str) and FAMILY_RE.fullmatch(value):
            consts[tgt.id] = value
        elif isinstance(value, dict):
            fams = {
                v for v in value.values()
                if isinstance(v, str) and FAMILY_RE.fullmatch(v)
            }
            if fams:
                dicts[tgt.id] = fams
    return consts, dicts


def _is_docstring_const(parents: Dict[int, ast.AST], node: ast.Constant) -> bool:
    parent = parents.get(id(node))
    if not isinstance(parent, ast.Expr):
        return False
    gp = parents.get(id(parent))
    return isinstance(
        gp, (ast.Module, ast.ClassDef, ast.FunctionDef, ast.AsyncFunctionDef)
    ) and gp.body and gp.body[0] is parent


def collect_emitted(sources: List[SourceFile],
                    skip_rels: Set[str]) -> Dict[str, Tuple[str, int]]:
    """Exposition family -> (file, line) for every emit-site literal.
    Literals inside prometheus_client Counter(...) calls are lifted to
    their ``_total`` exposition name; docstrings are ignored (prose)."""
    out: Dict[str, Tuple[str, int]] = {}
    for src in sources:
        if src.rel in skip_rels:
            continue
        parents: Dict[int, ast.AST] = {}
        counter_literals: Set[int] = set()
        for node in ast.walk(src.tree):
            for child in ast.iter_child_nodes(node):
                parents[id(child)] = node
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "Counter"
                and node.args
                and isinstance(node.args[0], ast.Constant)
            ):
                counter_literals.add(id(node.args[0]))
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Constant) or not isinstance(node.value, str):
                continue
            if _is_docstring_const(parents, node):
                continue
            for fam in FAMILY_RE.findall(node.value):
                if fam != node.value:
                    # Partial mention inside prose/comment-ish strings
                    # (format strings, error text): not an emit site.
                    continue
                name = fam
                if id(node) in counter_literals and not name.endswith("_total"):
                    name += "_total"
                out.setdefault(name, (src.rel, node.lineno))
    return out


def _normalize(name: str, registry: Dict[str, Dict[str, object]]) -> str:
    """Strip histogram exposition suffixes when the base is a registered
    histogram family."""
    if name in registry:
        return name
    for sfx in HIST_SUFFIXES:
        if name.endswith(sfx):
            base = name[: -len(sfx)]
            if registry.get(base, {}).get("kind") == "histogram":
                return base
    return name


def _dashboard_families(path: Path) -> Dict[str, str]:
    """family-name-as-written -> panel title, from every panel expr."""
    data = json.loads(path.read_text())
    out: Dict[str, str] = {}

    def walk_panels(panels: List[Dict[str, Any]]) -> None:
        for p in panels:
            title = p.get("title", "?")
            for t in p.get("targets", []):
                for fam in FAMILY_RE.findall(t.get("expr", "")):
                    out.setdefault(fam, title)
            if "panels" in p:
                walk_panels(p["panels"])

    walk_panels(data.get("panels", []))
    return out


def check_metrics(sources: List[SourceFile], cfg: C.Config) -> List[Violation]:
    out: List[Violation] = []
    reg_path = cfg.resolve(cfg.registry_path)
    if reg_path is None or not reg_path.exists():
        return [Violation(
            rule="SC302", file=cfg.registry_path or "<missing>", line=1,
            qualname="metric_registry",
            message="metric registry module missing", detail="missing",
        )]
    registry = parse_registry(reg_path)
    reg_rel = cfg.registry_path
    fake_rel = cfg.fake_engine_path

    emitted = collect_emitted(
        sources, skip_rels={reg_rel, fake_rel} if fake_rel else {reg_rel}
    )

    # SC301 / SC302 — emit sites vs registry.
    for fam, (file, line) in sorted(emitted.items()):
        if _normalize(fam, registry) not in registry:
            out.append(Violation(
                rule="SC301", file=file, line=line, qualname="metrics",
                message=(
                    f"metric family `{fam}` is emitted but absent from "
                    f"{reg_rel} (add it to REGISTRY with kind/layer/mirrors)"
                ),
                detail=fam,
            ))
    for fam, meta in sorted(registry.items()):
        source_name = meta.get("source_name", fam)
        if fam not in emitted and source_name not in emitted:
            out.append(Violation(
                rule="SC302", file=reg_rel, line=1, qualname="metrics",
                message=(
                    f"registry family `{fam}` has no emit site in the "
                    "package (renamed or removed without updating the "
                    "registry?)"
                ),
                detail=fam,
            ))

    # SC303 — fake-engine mirror.
    fake_path = cfg.resolve(cfg.fake_engine_path)
    vocab_path = cfg.resolve(cfg.vocabulary_path)
    if fake_path is not None and fake_path.exists():
        mirrored: Set[str] = set()
        fake_text = fake_path.read_text()
        mirrored.update(
            f for f in FAMILY_RE.findall(fake_text)
        )
        if vocab_path is not None and vocab_path.exists():
            consts, dicts = _vocabulary_constants(vocab_path)
            for cname, fam in consts.items():
                if re.search(rf"\b{re.escape(cname)}\b", fake_text):
                    mirrored.add(fam)
            for dname, fams in dicts.items():
                if re.search(rf"\b{re.escape(dname)}\b", fake_text):
                    mirrored.update(fams)
            # EngineObs.render_metrics() renders every histogram family
            # in the vocabulary dicts — using it IS the mirror.
            if "render_metrics" in fake_text or "EngineObs" in fake_text:
                for dname in ("TPU_REQUEST_HISTOGRAMS", "TPU_STEP_HISTOGRAMS",
                              "TPU_KV_HISTOGRAMS"):
                    mirrored.update(dicts.get(dname, set()))
        for fam, meta in sorted(registry.items()):
            if meta.get("layer") != "engine":
                continue
            if "fake_engine" not in meta.get("mirrors", ()):
                continue
            if fam not in mirrored and meta.get("source_name", fam) not in mirrored:
                out.append(Violation(
                    rule="SC303", file=cfg.fake_engine_path, line=1,
                    qualname="metrics",
                    message=(
                        f"engine family `{fam}` is not mirrored by the "
                        "fake engine (router/CI tests exercise the "
                        "contract through it)"
                    ),
                    detail=fam,
                ))

    # SC304 / SC305 — dashboard.
    dash_path = cfg.resolve(cfg.dashboard_path)
    if dash_path is not None and dash_path.exists():
        dash = _dashboard_families(dash_path)
        dash_norm = {_normalize(f, registry) for f in dash}
        for fam, meta in sorted(registry.items()):
            if "dashboard" in meta.get("mirrors", ()) and fam not in dash_norm:
                out.append(Violation(
                    rule="SC304", file=cfg.dashboard_path, line=1,
                    qualname="metrics",
                    message=(
                        f"family `{fam}` is flagged for the dashboard but "
                        "no panel expr references it"
                    ),
                    detail=fam,
                ))
        for fam, panel in sorted(dash.items()):
            if _normalize(fam, registry) not in registry:
                out.append(Violation(
                    rule="SC305", file=cfg.dashboard_path, line=1,
                    qualname="metrics",
                    message=(
                        f"dashboard panel '{panel}' queries `{fam}`, which "
                        "the registry doesn't know (stale panel or missing "
                        "registry entry)"
                    ),
                    detail=fam,
                ))

    # SC306 / SC307 — docs.
    docs_path = cfg.resolve(cfg.docs_path)
    if docs_path is not None and docs_path.exists():
        docs_text = docs_path.read_text()
        doc_fams = _prose_families(docs_text)
        doc_norm = {_normalize(f, registry) for f in doc_fams}
        for fam, meta in sorted(registry.items()):
            if "docs" in meta.get("mirrors", ()) and fam not in doc_norm:
                out.append(Violation(
                    rule="SC306", file=cfg.docs_path, line=1,
                    qualname="metrics",
                    message=(
                        f"family `{fam}` is flagged for the docs table but "
                        f"{cfg.docs_path} never mentions it"
                    ),
                    detail=fam,
                ))
        for fam in sorted(doc_fams):
            base = _normalize(fam, registry)
            # Docs may legitimately name template placeholders like
            # tpu:step_{schedule,...}_seconds — the regex won't match
            # those, so anything matched but unknown is real drift.
            if base not in registry:
                out.append(Violation(
                    rule="SC307", file=cfg.docs_path, line=1,
                    qualname="metrics",
                    message=(
                        f"docs reference `{fam}`, which the registry "
                        "doesn't know"
                    ),
                    detail=fam,
                ))
    return out
