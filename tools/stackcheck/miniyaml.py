"""Minimal YAML-subset parser for the deployment-contract rules (SC7).

stackcheck is pure stdlib by contract (it runs in the lint job with
nothing installed and never imports the code it checks), so it cannot
depend on PyYAML.  The helm values files use a disciplined subset —
block maps, block lists, scalars, empty flow ``{}``/``[]``, comments —
which this parser covers.  Anything outside the subset raises, loudly:
silently misparsing a values file would undermine the contract checks.

``parse(text)`` returns ``(data, key_lines)`` where ``key_lines`` maps
dotted key paths (list indices as ``[i]``) to 1-based line numbers, so
rules can anchor violations and look up inline allow comments.
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional, Tuple, Union

YamlValue = Union[None, bool, int, float, str, List["YamlValue"],
                  Dict[str, "YamlValue"]]

_KEY_RE = re.compile(r"^(?P<key>[A-Za-z0-9_./-]+|\"[^\"]*\"):(?:\s+(?P<rest>.*))?$")


class MiniYamlError(ValueError):
    pass


def _strip_comment(line: str) -> str:
    out = []
    quote: Optional[str] = None
    for i, ch in enumerate(line):
        if quote is not None:
            if ch == quote:
                quote = None
            out.append(ch)
            continue
        if ch in ("'", '"'):
            quote = ch
            out.append(ch)
            continue
        if ch == "#" and (i == 0 or line[i - 1] in (" ", "\t")):
            break
        out.append(ch)
    return "".join(out).rstrip()


def _scalar(text: str, lineno: int) -> YamlValue:
    t = text.strip()
    if t in ("", "~", "null", "Null", "NULL"):
        return None
    if t in ("true", "True"):
        return True
    if t in ("false", "False"):
        return False
    if t == "{}":
        return {}
    if t == "[]":
        return []
    if len(t) >= 2 and t[0] == t[-1] and t[0] in ("'", '"'):
        return t[1:-1]
    if re.fullmatch(r"[+-]?\d+", t):
        return int(t)
    if re.fullmatch(r"[+-]?\d*\.\d+", t):
        return float(t)
    if t.startswith(("{", "[", "|", ">", "&", "*")):
        raise MiniYamlError(
            f"line {lineno}: unsupported YAML construct {t!r} "
            "(stackcheck's mini parser covers the helm values subset only)"
        )
    return t


def parse(text: str) -> Tuple[YamlValue, Dict[str, int]]:
    lines: List[Tuple[int, str, int]] = []  # (indent, content, lineno)
    for ln, raw in enumerate(text.splitlines(), start=1):
        stripped = _strip_comment(raw)
        if not stripped.strip():
            continue
        if stripped.startswith("---"):
            continue
        indent = len(stripped) - len(stripped.lstrip(" "))
        lines.append((indent, stripped.strip(), ln))

    key_lines: Dict[str, int] = {}

    def parse_block(i: int, indent: int, path: str) -> Tuple[YamlValue, int]:
        if i >= len(lines) or lines[i][0] < indent:
            return None, i
        if lines[i][1].startswith("- ") or lines[i][1] == "-":
            return parse_list(i, lines[i][0], path)
        return parse_map(i, lines[i][0], path)

    def parse_map(i: int, indent: int, path: str) -> Tuple[YamlValue, int]:
        out: Dict[str, YamlValue] = {}
        while i < len(lines):
            ind, content, ln = lines[i]
            if ind < indent:
                break
            if ind > indent:
                raise MiniYamlError(f"line {ln}: unexpected indent")
            m = _KEY_RE.match(content)
            if m is None:
                raise MiniYamlError(f"line {ln}: expected `key:`, got {content!r}")
            key = m.group("key").strip('"')
            rest = m.group("rest")
            child_path = f"{path}.{key}" if path else key
            key_lines[child_path] = ln
            if rest is not None and rest.strip():
                out[key] = _scalar(rest, ln)
                i += 1
            else:
                value, i = parse_block(i + 1, indent + 1, child_path)
                out[key] = {} if value is None else value
        return out, i

    def parse_list(i: int, indent: int, path: str) -> Tuple[YamlValue, int]:
        out: List[YamlValue] = []
        while i < len(lines):
            ind, content, ln = lines[i]
            if ind < indent or not (content.startswith("- ") or content == "-"):
                break
            if ind > indent:
                raise MiniYamlError(f"line {ln}: unexpected list indent")
            item_path = f"{path}[{len(out)}]"
            key_lines[item_path] = ln
            rest = content[1:].strip()
            if not rest:
                value, i = parse_block(i + 1, indent + 1, item_path)
                out.append(value)
                continue
            m = _KEY_RE.match(rest)
            if m is not None:
                # Map item whose first key sits on the dash line: splice a
                # virtual line at the item's key indent and parse a map.
                dash_offset = content.index(rest[0])
                lines[i] = (ind + dash_offset, rest, ln)
                value, i = parse_map(i, ind + dash_offset, item_path)
                out.append(value)
            else:
                out.append(_scalar(rest, ln))
                i += 1
        return out, i

    data, i = parse_block(0, 0, "")
    if i != len(lines):
        raise MiniYamlError(
            f"line {lines[i][2]}: trailing content the mini parser "
            "could not attach"
        )
    return data, key_lines


def get_path(data: YamlValue, dotted: str) -> YamlValue:
    """Resolve ``a.b.c`` (no list indices) against parsed data; returns
    None when any segment is missing."""
    cur: YamlValue = data
    for part in dotted.split("."):
        if not isinstance(cur, dict) or part not in cur:
            return None
        cur = cur[part]
    return cur


def deep_merge(base: YamlValue, overlay: YamlValue) -> YamlValue:
    """Helm-style values merge: maps merge recursively, everything else
    (lists included) is replaced by the overlay."""
    if isinstance(base, dict) and isinstance(overlay, dict):
        out: Dict[str, YamlValue] = dict(base)
        for k, v in overlay.items():
            out[k] = deep_merge(out.get(k), v) if k in out else v
        return out
    return overlay if overlay is not None else base
