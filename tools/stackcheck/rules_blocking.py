"""Rule family SC1 — blocking-call reachability.

Invariant (PR 4, CHANGES.md): *no kvserver RPC or host-DMA wait is
reachable from ``Scheduler.schedule()`` or the step thread.*  The step
thread is the engine's only lane to the device: one blocking call under
it stalls every running sequence's decode for the full wait (the 5x
cold-replica ITL cliff PR 4 removed).

SC101  blocking call (socket/RPC/sleep/D2H-wait) reachable from a
       ``# stackcheck: root=step-thread`` function.
SC102  call into a contract-blocking package function (kvserver client
       RPC surface) reachable from a step root.
SC150  sync-blocking call inside an ``async def`` in router/ or
       engine/server/ — the event loop serves EVERY request; one blocked
       coroutine head-of-line-blocks all of them.
"""

from __future__ import annotations

import ast
from typing import List, Tuple

from tools.stackcheck import config as C
from tools.stackcheck.callgraph import CallGraph
from tools.stackcheck.core import Violation


def dotted_name(node: ast.AST) -> str:
    """Best-effort dotted rendering of a call target ('time.sleep',
    'sock.recv', '<expr>.attr' for computed receivers)."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return f"{dotted_name(node.value)}.{node.attr}"
    return "<expr>"


def _blocking_reason(call: ast.Call) -> str:
    """Why this call is considered blocking; '' = not blocking."""
    name = dotted_name(call.func)
    for prefix in C.BLOCKING_DOTTED_PREFIXES:
        if name == prefix or name.startswith(prefix):
            return name
    if isinstance(call.func, ast.Attribute):
        if call.func.attr in C.BLOCKING_ATTR_NAMES:
            return name
    return ""


def _path_str(graph: CallGraph, path: Tuple[str, ...]) -> str:
    return " -> ".join(p.split(":", 1)[-1] for p in path)


def check_blocking(graph: CallGraph, cfg: C.Config) -> List[Violation]:
    out: List[Violation] = []
    roots = graph.find_roots("step")
    reach = graph.reachable(
        roots,
        extra_edges=cfg.extra_edges,
        exclude=set(graph.find_boundaries("step")),
    )
    contract = {
        q for q in graph.functions
        if any(q.endswith(sfx) for sfx in C.BLOCKING_CONTRACT_SUFFIXES)
    }
    for q, path in reach.items():
        info = graph.functions[q]
        func_span = (info.def_line, info.end_line)
        for node in ast.walk(info.node):
            if not isinstance(node, ast.Call):
                continue
            why = _blocking_reason(node)
            if why:
                if info.src.allowed_at(node.lineno, "SC101", func_span):
                    continue
                out.append(Violation(
                    rule="SC101", file=info.src.rel, line=node.lineno,
                    qualname=q.split(":", 1)[-1],
                    message=(
                        f"blocking call `{why}` reachable from step root "
                        f"via {_path_str(graph, path)}"
                    ),
                    detail=why,
                ))
        # Contract-blocking package calls: flag at the CALLER edge into
        # the RPC surface (the kvserver client itself is allowed to
        # block — it runs on fetcher/writer threads everywhere legal).
        for callee in graph.edges.get(q, set()):
            if callee in contract and q not in contract:
                line = info.def_line
                # Find the call line for a usable location.
                mname = callee.rsplit(".", 1)[-1]
                for node in ast.walk(info.node):
                    if (
                        isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr == mname
                    ):
                        line = node.lineno
                        break
                if info.src.allowed_at(line, "SC102", func_span):
                    continue
                out.append(Violation(
                    rule="SC102", file=info.src.rel, line=line,
                    qualname=q.split(":", 1)[-1],
                    message=(
                        f"kvserver RPC `{callee.split(':', 1)[-1]}` "
                        f"reachable from step root via "
                        f"{_path_str(graph, path)}"
                    ),
                    detail=callee.split(":", 1)[-1],
                ))
    return out


def check_async_blocking(graph: CallGraph, cfg: C.Config) -> List[Violation]:
    """SC150: sync-blocking calls inside async defs under async_dirs."""
    out: List[Violation] = []
    scopes = tuple(d.rstrip("/") + "/" for d in cfg.async_dirs)
    contract_names = set(C.ASYNC_CONTRACT_NAMES)
    for q, info in graph.functions.items():
        if not info.is_async:
            continue
        if not any(info.src.rel.startswith(s) for s in scopes):
            continue
        func_span = (info.def_line, info.end_line)
        # Nested defs inside the async function run on whatever thread
        # calls them, not necessarily the event loop — scan only the
        # async function's own statements.
        nested: Set[int] = set()
        for node in ast.walk(info.node):
            if node is not info.node and isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                for sub in ast.walk(node):
                    nested.add(id(sub))
        for node in ast.walk(info.node):
            if id(node) in nested or not isinstance(node, ast.Call):
                continue
            why = _blocking_reason(node)
            if not why and isinstance(node.func, ast.Attribute):
                if node.func.attr in contract_names:
                    why = dotted_name(node.func)
            if not why:
                continue
            if info.src.allowed_at(node.lineno, "SC150", func_span):
                continue
            out.append(Violation(
                rule="SC150", file=info.src.rel, line=node.lineno,
                qualname=q.split(":", 1)[-1],
                message=(
                    f"sync-blocking call `{why}` inside async def "
                    f"{info.name} (event-loop stall: every in-flight "
                    "request waits)"
                ),
                detail=why,
            ))
    return out
