"""stackcheck — AST/call-graph invariant checker for the TPU stack.

Turns the prose invariants PRs 1–5 established (no blocking under the
scheduler/step thread, lockstep determinism, the three-way metrics
contract, default-off gate safety) into a static-analysis pass that
fails CI.  Pure stdlib; never imports the code under analysis.

Entry points:
    python -m tools.stackcheck            # CLI (CI lint job)
    tools.stackcheck.run_checks(cfg)      # library (tier-1 tests)

See docs/static-analysis.md for the invariant catalog and annotation
syntax.
"""

from __future__ import annotations

from pathlib import Path
from typing import Dict, List, Optional, Union

from tools.stackcheck.callgraph import CallGraph
from tools.stackcheck.config import Config
from tools.stackcheck.core import (
    Baseline,
    Violation,
    annotation_violations,
    load_baseline,
    load_sources,
    write_baseline,
)
from tools.stackcheck.rules_blocking import check_async_blocking, check_blocking
from tools.stackcheck.rules_deployment import check_deployment
from tools.stackcheck.rules_determinism import check_determinism
from tools.stackcheck.rules_gates import check_gates
from tools.stackcheck.rules_lifecycle import check_lifecycle
from tools.stackcheck.rules_locks import check_locks
from tools.stackcheck.rules_metrics import check_metrics

RULE_FAMILIES = {
    "annotations": ("SC001",),
    "blocking": ("SC101", "SC102", "SC150"),
    "determinism": ("SC201", "SC202", "SC203"),
    "metrics": ("SC301", "SC302", "SC303", "SC304", "SC305", "SC306", "SC307"),
    "gates": ("SC401", "SC402", "SC403"),
    "locks": ("SC501", "SC502", "SC503"),
    "lifecycle": ("SC601", "SC602", "SC603"),
    "deployment": (
        "SC701", "SC702", "SC703", "SC704", "SC705", "SC706", "SC707",
        "SC708",
    ),
}

# `--rules SC5,SC6,SC7` style shorthands: rule-id prefix -> family name.
FAMILY_ALIASES = {
    "SC0": "annotations",
    "SC1": "blocking",
    "SC2": "determinism",
    "SC3": "metrics",
    "SC4": "gates",
    "SC5": "locks",
    "SC6": "lifecycle",
    "SC7": "deployment",
}

__all__ = [
    "Config", "Violation", "run_checks", "resolve_families",
    "RULE_FAMILIES", "FAMILY_ALIASES",
]


def resolve_families(names: List[str]) -> List[str]:
    """Map user-facing family selectors (family names, `SC5`-style
    prefixes, or full rule ids like `SC501`) to family names.  Raises
    ValueError on anything unknown."""
    out: List[str] = []
    for name in names:
        if name in RULE_FAMILIES:
            out.append(name)
            continue
        alias = FAMILY_ALIASES.get(name[:3]) if name.startswith("SC") else None
        if alias is not None:
            out.append(alias)
            continue
        raise ValueError(
            f"unknown rule family {name!r} (families: "
            f"{', '.join(RULE_FAMILIES)}; shorthands: "
            f"{', '.join(FAMILY_ALIASES)})"
        )
    return out


def run_checks(
    cfg: Config, families: Optional[List[str]] = None
) -> List[Violation]:
    """Run the selected rule families (default: all) and return every
    violation NOT suppressed by an inline annotation.  Baseline
    filtering is the caller's business (the CLI applies it; tests
    usually want the raw list)."""
    wanted = set(resolve_families(families) if families else RULE_FAMILIES)
    sources = load_sources(cfg.repo_root, list(cfg.package_dirs))
    violations: List[Violation] = []
    if "annotations" in wanted:
        violations += annotation_violations(sources)
    if wanted & {"blocking", "determinism", "locks", "lifecycle"}:
        graph = CallGraph(sources)
        if "blocking" in wanted:
            violations += check_blocking(graph, cfg)
            violations += check_async_blocking(graph, cfg)
        if "determinism" in wanted:
            violations += check_determinism(graph, cfg)
        if "locks" in wanted:
            violations += check_locks(graph, cfg)
        if "lifecycle" in wanted:
            violations += check_lifecycle(graph, cfg)
    if "metrics" in wanted:
        violations += check_metrics(sources, cfg)
    if "gates" in wanted:
        violations += check_gates(sources, cfg)
    if "deployment" in wanted:
        violations += check_deployment(cfg)
    violations.sort(key=lambda v: (v.file, v.line, v.rule, v.detail))
    return violations


def apply_baseline(
    violations: List[Violation], baseline: Union[Path, Baseline]
) -> Dict[str, List[Violation]]:
    """Split violations into {'new': [...], 'baselined': [...]}.  Accepts
    a pre-loaded Baseline so the CLI parses the file only once."""
    if isinstance(baseline, Path):
        baseline = load_baseline(baseline)
    new = [v for v in violations if v.key not in baseline]
    old = [v for v in violations if v.key in baseline]
    return {"new": new, "baselined": old}


def update_baseline(
    violations: List[Violation], baseline_path: Path
) -> Optional[str]:
    previous = load_baseline(baseline_path)
    return write_baseline(baseline_path, violations, previous)
