"""Rule family SC7 — the deployment contract.

Invariant (PR 5, docs/robustness.md): *the chart and the binaries agree.*
The helm templates hardcode flag names, probe paths, ports, and the
drain-grace threading; the binaries own the argparse surfaces and HTTP
routes; `values.yaml`, `values.schema.json`, and the docs table all
restate pieces of the same contract.  Nothing ties them together at
runtime — a renamed flag or a probe pointing at a route that moved
deploys fine and fails in production.  This family cross-checks the
five surfaces the same way SC3xx cross-checks metrics:

SC701  a flag templated into a container command/args does not exist on
       that binary's argparse surface.
SC702  a values key is templated into a flag but its default in
       values.yaml differs from the flag's argparse default — the
       chart-default deployment silently diverges from the documented
       binary default.

Every SC7 sub-rule honors the inline allow: a ``# stackcheck:
allow=SC70x reason=...`` comment on (or directly above) the flagged
line of the values file, template, or docs table (in markdown, inside
an HTML comment on the row) suppresses a deliberate divergence with a
recorded reason.
SC703  a probe path (httpGet) or preStop hook path in a template/values
       probe block is not a registered route on the target server — with
       the right method: kubelet probes GET, preStop hooks POST, so a
       POST-only route under a probe still flags — or a probe targets a
       port name the template never declares.
SC704  the drain contract is broken: the template does not thread the
       spec's ``drainGraceSeconds`` into ``--drain-grace-s``, does not
       source ``terminationGracePeriodSeconds`` from values, or a
       shipped values file (base or overlay, helm-merged) sets
       ``terminationGracePeriodSeconds <= drainGraceSeconds`` — strict
       excess required: the termination countdown also covers the
       preStop hook and teardown, so equality still SIGKILLs a drain
       that uses its full budget.
SC705  a values key referenced by a template is absent from
       ``values.schema.json`` (typos in overrides validate clean).
SC706  a row of the docs/robustness.md "Helm values" table names a key
       missing from values.yaml, or documents a default that drifted.
SC707  the disagg role-pool contract is broken: the role label key the
       engine template renders on role-pool Deployments differs from the
       key the router will select on (its ``--k8s-role-label`` — the
       templated value, else the argparse default); or a ``roles[].role``
       value in a shipped values file is outside the engine binary's
       ``--disagg-role`` choices.  Both deploy fine and silently run the
       fleet fused — role discovery returns None for every pod.
SC709  the multi-host pod-group contract is broken: a modelSpec entry's
       engine mesh (dp·tp·sp) does not equal ``tpuNumWorkers ×
       requestTPU`` (the slice deploys fine and deadlocks at the FIRST
       collective — jax sees a different chip count than the mesh
       expects); the client Service is not pinned to ordinal 0 (clients
       would round-robin onto followers that serve only probes); the
       headless bootstrap service does not publish not-ready addresses
       (workers must resolve each other BEFORE any passes readiness —
       the group can never form); slice pods are not labeled/covered by
       a ``maxUnavailable: 0`` slice PDB or not excluded from the
       generic release PDB (one voluntary eviction decapitates a live
       slice); or the StatefulSet branch lacks the preStop drain hook /
       terminationGracePeriodSeconds (a follower SIGTERM would kill the
       slice's in-flight collectives with no drain relay).
SC708  the autoscaling PromQL contract is broken: a
       ``tpu:``/``tpu_router:`` family referenced by an
       ``observability/*.yaml`` surface or a helm HPA template does not
       exist in ``metric_registry.py`` (renamed or never emitted — the
       adapter rule matches nothing and the HPA silently never scales);
       or an HPA custom-metric name is not the ``as:`` rename of any
       prometheus-adapter rule (the custom metrics API would 404 it).

All YAML parsing is the stdlib-only subset parser (miniyaml.py); no
template is rendered — the checks read the template source directly, so
they cover every branch, not just one values combination.
"""

from __future__ import annotations

import ast
import json
import re
from pathlib import Path
from typing import Dict, List, Optional, Set, Tuple

from tools.stackcheck import config as C
from tools.stackcheck import miniyaml
from tools.stackcheck.core import Violation
from tools.stackcheck.rules_gates import _argparse_flags

_FLAG_ITEM_RE = re.compile(r'^\s*-\s+"(--[a-z0-9-]+)"\s*$')
_VALUE_ITEM_RE = re.compile(r"^\s*-\s+(.+?)\s*$")
_VALUES_REF_RE = re.compile(r"\$?\.Values\.([A-Za-z0-9_.]+)")
_MODEL_REF_RE = re.compile(r"\$m\.([A-Za-z0-9_.]+)")
_MODEL_RANGE_RE = re.compile(
    r"range\s+\$m\s*:=\s*\.Values\.([A-Za-z0-9_.]+)"
)
_HTTP_PATH_RE = re.compile(r"^\s*path:\s*(/[A-Za-z0-9_/-]*)\s*$")
_PRESTOP_PATH_RE = re.compile(r"127\.0\.0\.1:\{\{[^}]*\}\}(/[A-Za-z0-9_/-]+)")
_NAMED_PORT_RE = re.compile(r'-\s+name:\s+"([a-z0-9-]+)"\s*\n\s*containerPort:')
_YAML_ALLOW_RE = re.compile(
    r"#\s*stackcheck:\s*allow=(?P<rules>[A-Z0-9,]+)\s+reason=\S"
)


def _yaml_allowed(lines: List[str], line: int, rule: str) -> bool:
    """Inline allow for YAML/values files: a `# stackcheck: allow=SC70x
    reason=...` comment on the key's line or the line above."""
    for ln in (line, line - 1):
        if 1 <= ln <= len(lines):
            m = _YAML_ALLOW_RE.search(lines[ln - 1])
            if m and rule in m.group("rules").split(","):
                return True
    return False


def _normalize_default(value: object) -> Optional[str]:
    """Comparable rendering of a default (None for 'no default')."""
    if value is None or value is ...:
        return None
    if isinstance(value, (dict, list)):
        # A bare `key:` parses as {} (YAML null) and mappings/lists are
        # never flag defaults — treat as "no default", not the str() of
        # the container (which would fabricate an SC702 mismatch).
        return None
    if isinstance(value, bool):
        return "true" if value else "false"
    if isinstance(value, (int, float)):
        f = float(value)
        return str(int(f)) if f.is_integer() else repr(f)
    s = str(value)
    return s if s != "" else None


def _collect_template_flags(
    text: str,
) -> List[Tuple[str, int, Optional[str]]]:
    """(flag, line, values_path_or_None) for every `- "--flag"` list item
    in a template; the values path comes from the next list item when it
    references `.Values.*` (modelSpec `$m.*` refs return None — per-model
    fields have no chart-level default to compare)."""
    out: List[Tuple[str, int, Optional[str]]] = []
    lines = text.splitlines()
    for i, line in enumerate(lines):
        m = _FLAG_ITEM_RE.match(line)
        if m is None:
            continue
        flag = m.group(1)
        values_path: Optional[str] = None
        for nxt in lines[i + 1:i + 3]:
            if _FLAG_ITEM_RE.match(nxt):
                break  # boolean flag: next item is another flag
            vm = _VALUE_ITEM_RE.match(nxt)
            if vm is None:
                continue
            ref = _VALUES_REF_RE.search(vm.group(1))
            if ref is not None:
                values_path = ref.group(1)
            break
        out.append((flag, i + 1, values_path))
    return out


def _collect_values_refs(text: str) -> List[Tuple[str, int]]:
    """Every values key path a template references, with its line:
    `.Values.a.b` directly, `$m.x` mapped through whatever values list
    the template's own `range $m := .Values.<path>` binds it to (no
    binding in this template -> `$m` refs are skipped rather than
    validated against a guessed subtree)."""
    out: List[Tuple[str, int]] = []
    binding = _MODEL_RANGE_RE.search(text)
    model_base = f"{binding.group(1)}[]" if binding else None
    for i, line in enumerate(text.splitlines()):
        for m in _VALUES_REF_RE.finditer(line):
            out.append((m.group(1), i + 1))
        if model_base is not None:
            for m in _MODEL_REF_RE.finditer(line):
                out.append((f"{model_base}.{m.group(1)}", i + 1))
    return out


def _schema_has(schema: Dict[str, object], dotted: str) -> bool:
    """Resolve a dotted key path (with `[]` for array items) against a
    JSON-schema properties tree.  A subtree typed plain `object` with no
    `properties` (free-form maps like labels/resources) accepts any
    deeper path."""
    node: object = schema
    for raw in dotted.split("."):
        parts = [raw]
        if raw.endswith("[]"):
            parts = [raw[:-2], "[]"]
        for part in parts:
            if not isinstance(node, dict):
                return False
            if part == "[]":
                if "items" not in node:
                    return False
                node = node["items"]
                continue
            props = node.get("properties")
            if not isinstance(props, dict):
                # Free-form object (additionalProperties / untyped):
                # accepts any key below it.
                return "properties" not in node
            if part not in props:
                return False
            node = props[part]
    return True


def _server_routes(path: Path) -> Set[Tuple[str, str]]:
    """(METHOD, path) literals from aiohttp route registrations:
    `app.router.add_get("/p", h)` and `@routes.get("/p")` styles."""
    routes: Set[Tuple[str, str]] = set()
    tree = ast.parse(path.read_text(), filename=str(path))
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if not isinstance(fn, ast.Attribute):
            continue
        method: Optional[str] = None
        if fn.attr.startswith("add_") and fn.attr[4:] in (
            "get", "post", "put", "delete", "patch", "head"
        ):
            method = fn.attr[4:].upper()
        elif fn.attr in ("get", "post", "put", "delete", "patch", "head"):
            method = fn.attr.upper()
        if method is None or not node.args:
            continue
        arg = node.args[0]
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            if arg.value.startswith("/"):
                routes.add((method, arg.value))
    return routes


_ANY_RANGE_RE = re.compile(
    r"range\s+(\$\w+)\s*:=\s*\$?\.Values\.([A-Za-z0-9_.]+)"
)
_FLAG_LITERAL_ITEM_RE = re.compile(r'^\s*-\s+"([^"]*)"\s*$')


def _template_flag_value(text: str, flag: str) -> Optional[str]:
    """The value item following a templated ``- "--flag"``: a literal
    string, or the values-default of a ``.Values.*`` ref (resolved by the
    caller) — returned as ("literal", s) / ("ref", path) packed into a
    prefixed string, None when the flag is absent or value-less."""
    lines = text.splitlines()
    for i, line in enumerate(lines):
        m = _FLAG_ITEM_RE.match(line)
        if m is None or m.group(1) != flag:
            continue
        for nxt in lines[i + 1:i + 3]:
            if _FLAG_ITEM_RE.match(nxt):
                return None  # boolean flag
            ref = _VALUES_REF_RE.search(nxt)
            if ref is not None:
                return "ref:" + ref.group(1)
            lit = _FLAG_LITERAL_ITEM_RE.match(nxt)
            if lit is not None:
                return "lit:" + lit.group(1)
            break
    return None


def _role_label_keys(text: str, roles_values_path: str) -> List[Tuple[str, int]]:
    """Label keys whose VALUE is the role field of the roles-range
    variable: ``<key>: {{ $r.role ... }}`` inside a template that binds
    ``range $r := .Values.<roles_values_path>``.  Returns (key, line)."""
    var: Optional[str] = None
    for m in _ANY_RANGE_RE.finditer(text):
        if m.group(2) == roles_values_path:
            var = m.group(1)
            break
    if var is None:
        return []
    key_re = re.compile(
        r"^\s*([A-Za-z0-9./_-]+):\s*\{\{-?\s*" + re.escape(var) + r"\.role\b"
    )
    out: List[Tuple[str, int]] = []
    for i, line in enumerate(text.splitlines()):
        km = key_re.match(line)
        if km is not None:
            out.append((km.group(1), i + 1))
    return out


def _check_role_contract(
    cfg: C.Config,
    values: miniyaml.YamlValue,
    values_lines: List[str],
    value_key_lines: Dict[str, int],
    overlays: List[Tuple[str, "miniyaml.YamlValue", List[str], Dict[str, int]]],
) -> List[Violation]:
    """SC707 — see module docstring."""
    out: List[Violation] = []
    rc = cfg.role_contract
    if rc is None:
        return out
    engine_tmpl = cfg.resolve(rc.engine_template)
    router_tmpl = cfg.resolve(rc.router_template)
    if engine_tmpl is None or not engine_tmpl.exists():
        return out
    engine_text = engine_tmpl.read_text()
    engine_lines = engine_text.splitlines()
    label_keys = _role_label_keys(engine_text, rc.roles_values_path)
    if not label_keys:
        return out  # no role pools rendered in this chart

    # The key the router will read roles from: the template's
    # --k8s-role-label value (literal or values default), falling back to
    # the router binary's argparse default.
    router_key: Optional[str] = None
    router_src = rc.router_template
    if router_tmpl is not None and router_tmpl.exists():
        packed = _template_flag_value(
            router_tmpl.read_text(), rc.role_label_flag
        )
        if packed is not None:
            kind, _, payload = packed.partition(":")
            if kind == "lit":
                router_key = payload
            elif kind == "ref":
                resolved = miniyaml.get_path(values, payload)
                if isinstance(resolved, str) and resolved:
                    router_key = resolved
                    router_src = cfg.helm_values_path or "values.yaml"
    role_choices: Optional[Tuple[str, ...]] = None
    router_arg_path = cfg.resolve(rc.router_argparse_file)
    if router_key is None and router_arg_path is not None \
            and router_arg_path.exists():
        from tools.stackcheck.core import SourceFile

        rflags = _argparse_flags(SourceFile(
            router_arg_path, rc.router_argparse_file,
            router_arg_path.read_text(),
        ))
        info = rflags.get(rc.role_label_flag)
        if info is not None and isinstance(info.get("default"), str):
            router_key = str(info["default"])
            router_src = rc.router_argparse_file
    engine_arg_path = cfg.resolve(rc.engine_argparse_file)
    if engine_arg_path is not None and engine_arg_path.exists():
        from tools.stackcheck.core import SourceFile

        eflags = _argparse_flags(SourceFile(
            engine_arg_path, rc.engine_argparse_file,
            engine_arg_path.read_text(),
        ))
        info = eflags.get(rc.role_flag)
        choices_obj = info.get("choices") if info is not None else None
        if isinstance(choices_obj, (list, tuple)):
            role_choices = tuple(str(c) for c in choices_obj)

    if router_key is None:
        out.append(Violation(
            rule="SC707", file=rc.engine_template, line=label_keys[0][1],
            qualname=rc.roles_values_path,
            message=(
                "engine template renders role-labeled pods but neither "
                f"the router template nor {rc.router_argparse_file} "
                f"defines {rc.role_label_flag} — the router can never "
                "select roles; the fleet silently runs fused"
            ),
            detail="role_label_flag_missing",
        ))
    else:
        for key, line in label_keys:
            if key == router_key:
                continue
            if _yaml_allowed(engine_lines, line, "SC707"):
                continue
            out.append(Violation(
                rule="SC707", file=rc.engine_template, line=line,
                qualname=rc.roles_values_path,
                message=(
                    f"engine role pools label pods `{key}: <role>` but "
                    f"the router selects roles via `{router_key}` "
                    f"({router_src}) — role discovery returns None for "
                    "every pod and the fleet silently runs fused"
                ),
                detail=f"role_label:{key}!={router_key}",
            ))

    # roles[].role values in every shipped values file must be within the
    # engine binary's --disagg-role choices.
    if role_choices:
        for rel, merged, file_lines, file_key_lines in overlays:
            roles_value = miniyaml.get_path(merged, rc.roles_values_path)
            if not isinstance(roles_value, list):
                continue
            for idx, entry in enumerate(roles_value):
                role = entry.get("role") if isinstance(entry, dict) else None
                if role is None or str(role) in role_choices:
                    continue
                line = file_key_lines.get(
                    rc.roles_values_path,
                    file_key_lines.get(
                        rc.roles_values_path.split(".")[0], 1
                    ),
                )
                if _yaml_allowed(file_lines, line, "SC707"):
                    continue
                out.append(Violation(
                    rule="SC707", file=rel, line=line,
                    qualname=f"{rc.roles_values_path}[{idx}]",
                    message=(
                        f"roles[{idx}].role = {role!r} is outside the "
                        f"engine binary's {rc.role_flag} choices "
                        f"{list(role_choices)} — the pool pod would "
                        "crash-loop on argparse error"
                    ),
                    detail=f"role_value:{role}",
                ))
    return out


def _yaml_docs(text: str) -> List[Tuple[int, str]]:
    """Split template source into YAML documents on `---` lines,
    returning (start_line, doc_text) pairs — template-source-level, so
    every branch of every document is covered."""
    docs: List[Tuple[int, str]] = []
    start = 1
    current: List[str] = []
    for i, line in enumerate(text.splitlines()):
        if line.strip() == "---":
            if any(ln.strip() for ln in current):
                docs.append((start, "\n".join(current)))
            current = []
            start = i + 2
        else:
            current.append(line)
    if any(ln.strip() for ln in current):
        docs.append((start, "\n".join(current)))
    return docs


def _as_int(value: object, default: Optional[int] = None) -> Optional[int]:
    """Strict int coercion for YAML scalars (bool is NOT an int here)."""
    if value is None:
        return default
    if isinstance(value, bool):
        return default
    if isinstance(value, int):
        return value
    if isinstance(value, float):
        return int(value)
    if isinstance(value, str):
        try:
            return int(value)
        except ValueError:
            return default
    return default


def _check_slice_contract(
    cfg: C.Config,
    overlays: List[Tuple[str, "miniyaml.YamlValue", List[str], Dict[str, int]]],
) -> List[Violation]:
    """SC709 — see module docstring."""
    out: List[Violation] = []
    sc = cfg.slice_contract
    if sc is None:
        return out

    # (a) mesh-product arithmetic in every shipped values file: the
    # engine rejects a bad mesh at boot only AFTER the pods scheduled —
    # and a mesh that merely mismatches the chip count deadlocks at the
    # first collective instead.  tpuNumWorkers × requestTPU must equal
    # dp·tp·sp.
    for rel, merged, file_lines, file_key_lines in overlays:
        models = miniyaml.get_path(merged, sc.modelspec_values_path)
        if not isinstance(models, list):
            continue
        for entry in models:
            if not isinstance(entry, dict):
                continue
            workers = _as_int(entry.get(sc.workers_key), default=1)
            if workers is None or workers <= 1:
                continue
            name = str(entry.get("name", "?"))
            line = file_key_lines.get(
                sc.modelspec_values_path,
                file_key_lines.get(sc.modelspec_values_path.split(".")[0], 1),
            )
            chips = _as_int(entry.get(sc.chips_key))
            if chips is None:
                continue  # CPU/fake slice: no chip arithmetic to check
            eng_raw = entry.get("engineConfig")
            eng: Dict[object, object] = (
                eng_raw if isinstance(eng_raw, dict) else {}
            )
            mesh = 1
            for axis in ("dataParallel", "tensorParallel",
                         "sequenceParallel"):
                mesh *= _as_int(eng.get(axis), default=1) or 1
            if mesh != workers * chips:
                if _yaml_allowed(file_lines, line, "SC709"):
                    continue
                out.append(Violation(
                    rule="SC709", file=rel, line=line,
                    qualname=sc.modelspec_values_path,
                    message=(
                        f"modelSpec '{name}': engine mesh dp*tp*sp = "
                        f"{mesh} but the slice provides {sc.workers_key} "
                        f"({workers}) x {sc.chips_key} ({chips}) = "
                        f"{workers * chips} chips — the group deploys "
                        "fine and deadlocks at the first collective"
                    ),
                    detail=f"mesh_product:{name}",
                ))

    # (b)/(c) template-structure checks, active only when the engine
    # template renders a pod-group (StatefulSet) branch at all.
    engine_tmpl = cfg.resolve(sc.engine_template)
    if engine_tmpl is None or not engine_tmpl.exists():
        return out
    engine_text = engine_tmpl.read_text()
    engine_lines = engine_text.splitlines()
    sts_kind_re = re.compile(r"^\s*kind:\s*StatefulSet\s*$", re.M)
    sts_docs = [
        (ln, doc) for ln, doc in _yaml_docs(engine_text)
        if sts_kind_re.search(doc)
    ]
    if not sts_docs:
        return out  # no pod-group mode in this chart

    def _flag(
        file: str, line: int, lines: List[str], message: str, detail: str
    ) -> None:
        if not _yaml_allowed(lines, line, "SC709"):
            out.append(Violation(
                rule="SC709", file=file, line=line,
                qualname=sc.engine_template, message=message, detail=detail,
            ))

    if sc.slice_label_key not in engine_text:
        _flag(
            sc.engine_template, sts_docs[0][0], engine_lines,
            f"pod-group branch renders no `{sc.slice_label_key}` label — "
            "slice pods are indistinguishable from single-host pods, so "
            "neither the generic-PDB exclusion nor the slice PDB can "
            "select them",
            "slice_label_missing",
        )
    if "statefulset.kubernetes.io/pod-name" not in engine_text:
        _flag(
            sc.engine_template, sts_docs[0][0], engine_lines,
            "client-facing Service is not pinned to ordinal 0 "
            "(statefulset.kubernetes.io/pod-name): clients would "
            "round-robin onto followers that serve only probes",
            "client_service_unpinned",
        )
    has_published_headless = any(
        "clusterIP: None" in doc and "publishNotReadyAddresses: true" in doc
        for _, doc in _yaml_docs(engine_text)
    )
    if not has_published_headless:
        _flag(
            sc.engine_template, sts_docs[0][0], engine_lines,
            "no headless service with `publishNotReadyAddresses: true`: "
            "workers must resolve each other BEFORE any passes readiness "
            "(coordination precedes serving) — the jax.distributed "
            "bootstrap can never form the group",
            "headless_not_ready_unpublished",
        )
    for ln, doc in sts_docs:
        if "preStop" not in doc:
            _flag(
                sc.engine_template, ln, engine_lines,
                "StatefulSet branch has no preStop drain hook: a member "
                "SIGTERM would kill the slice's in-flight collectives "
                "with no drain relay",
                "sts_prestop_missing",
            )
        if "terminationGracePeriodSeconds" not in doc:
            _flag(
                sc.engine_template, ln, engine_lines,
                "StatefulSet branch does not set "
                "terminationGracePeriodSeconds: kubelet's default 30s "
                "SIGKILLs a slice-wide drain that relays through the "
                "leader",
                "sts_termination_missing",
            )

    pdb_tmpl = cfg.resolve(sc.pdb_template)
    pdb_text = (
        pdb_tmpl.read_text()
        if pdb_tmpl is not None and pdb_tmpl.exists() else ""
    )
    pdb_lines = pdb_text.splitlines()
    pdb_docs = [
        (ln, doc) for ln, doc in _yaml_docs(pdb_text)
        if "PodDisruptionBudget" in doc
    ]
    zero_re = re.compile(r"maxUnavailable:\s*0\s*$", re.M)
    slice_pdbs = [
        (ln, doc) for ln, doc in pdb_docs
        if zero_re.search(doc) and sc.slice_label_key in doc
    ]
    generic_pdbs = [
        (ln, doc) for ln, doc in pdb_docs if (ln, doc) not in slice_pdbs
    ]
    if not slice_pdbs:
        _flag(
            sc.pdb_template or "<missing>", 1, pdb_lines,
            "no slice-group PodDisruptionBudget with `maxUnavailable: 0` "
            f"selecting `{sc.slice_label_key}`: voluntary evictions can "
            "take a member of a live slice (the group wedges at its next "
            "collective and restarts)",
            "slice_pdb_missing",
        )
    for ln, doc in generic_pdbs:
        if sc.slice_label_key in doc and "DoesNotExist" in doc:
            continue
        _flag(
            sc.pdb_template, ln, pdb_lines,
            "generic release PDB does not exclude slice pods "
            f"(`{sc.slice_label_key}` DoesNotExist): its maxUnavailable "
            "budget lets ONE eviction decapitate a live slice",
            "generic_pdb_includes_slices",
        )
    return out


# HPA custom-metric reference: `metric:` followed by its `name:` key.
_HPA_METRIC_NAME_RE = re.compile(
    r"metric:\s*\n\s*name:\s*\"?([A-Za-z0-9_:-]+)\"?"
)
# prometheus-adapter rename: the `as:` key inside a rule's name block.
_ADAPTER_AS_RE = re.compile(r"^\s*as:\s*\"?([A-Za-z0-9_]+)\"?\s*$")


def _check_promql_registry(cfg: C.Config) -> List[Violation]:
    """SC708 — see module docstring.  Skips silently when the tree has
    no metric registry (fixture trees exercising only SC70x)."""
    out: List[Violation] = []
    reg_path = cfg.resolve(cfg.registry_path)
    if reg_path is None or not reg_path.exists():
        return out
    from tools.stackcheck.rules_metrics import (
        FAMILY_RE,
        _normalize,
        parse_registry,
    )

    registry = parse_registry(reg_path)

    adapter_names: Set[str] = set()
    adapter_rel = cfg.prom_adapter_path
    adapter_path = cfg.resolve(adapter_rel)
    if adapter_path is not None and adapter_path.exists():
        for line in adapter_path.read_text().splitlines():
            m = _ADAPTER_AS_RE.match(line)
            if m is not None:
                adapter_names.add(m.group(1))

    surfaces = list(cfg.observability_yaml_paths) + list(cfg.hpa_template_paths)
    for rel in surfaces:
        path = cfg.resolve(rel)
        if path is None or not path.exists():
            continue
        text = path.read_text()
        lines = text.splitlines()
        # (a) every referenced family must exist in the registry.
        seen: Set[str] = set()
        for i, line in enumerate(lines):
            for fam in FAMILY_RE.findall(line):
                if fam in seen:
                    continue
                seen.add(fam)
                if _normalize(fam, registry) in registry:
                    continue
                if _yaml_allowed(lines, i + 1, "SC708"):
                    continue
                out.append(Violation(
                    rule="SC708", file=rel, line=i + 1,
                    qualname="autoscaling",
                    message=(
                        f"`{fam}` is not a registered metric family "
                        f"({cfg.registry_path}) — the adapter rule/query "
                        "matches nothing and the HPA silently never "
                        "scales (renamed family, or missing registry "
                        "entry)"
                    ),
                    detail=fam,
                ))
        # (b) every HPA custom-metric name must be an adapter `as:`
        # rename (only stack-owned `tpu*` names are checked — resource
        # metrics like cpu are out of scope).
        if not adapter_names:
            continue
        for m in _HPA_METRIC_NAME_RE.finditer(text):
            name = m.group(1)
            if not name.startswith("tpu"):
                continue
            if name in adapter_names:
                continue
            line = text[: m.start()].count("\n") + 2  # the `name:` line
            if _yaml_allowed(lines, line, "SC708"):
                continue
            out.append(Violation(
                rule="SC708", file=rel, line=line,
                qualname="autoscaling",
                message=(
                    f"HPA references custom metric `{name}` but no "
                    f"prometheus-adapter rule in {adapter_rel} exposes "
                    "it (`as:` rename missing) — the custom metrics API "
                    "404s and the HPA silently never scales"
                ),
                detail=f"hpa:{name}",
            ))
    return out


def check_deployment(cfg: C.Config) -> List[Violation]:
    out: List[Violation] = []
    out.extend(_check_promql_registry(cfg))
    values_path = cfg.resolve(cfg.helm_values_path)
    if values_path is None or not values_path.exists():
        return out  # no chart in this tree: nothing to check
    values_text = values_path.read_text()
    values_lines = values_text.splitlines()
    values, value_key_lines = miniyaml.parse(values_text)

    schema: Optional[Dict[str, object]] = None
    schema_path = cfg.resolve(cfg.helm_schema_path)
    if schema_path is not None and schema_path.exists():
        loaded = json.loads(schema_path.read_text())
        if isinstance(loaded, dict):
            schema = loaded

    for surface in cfg.deployment_surfaces:
        tmpl_path = cfg.resolve(surface.template)
        if tmpl_path is None or not tmpl_path.exists():
            continue
        tmpl_text = tmpl_path.read_text()
        tmpl_lines = tmpl_text.splitlines()

        argparse_path = cfg.resolve(surface.argparse_file)
        flags: Dict[str, Dict[str, object]] = {}
        if argparse_path is not None and argparse_path.exists():
            from tools.stackcheck.core import SourceFile

            src = SourceFile(
                argparse_path, surface.argparse_file,
                argparse_path.read_text(),
            )
            flags = _argparse_flags(src)

        routes: Set[Tuple[str, str]] = set()
        for route_rel in surface.route_files:
            route_path = cfg.resolve(route_rel)
            if route_path is not None and route_path.exists():
                routes |= _server_routes(route_path)
        # kubelet httpGet probes issue GET: a path registered only as
        # POST (e.g. /drain) would answer the probe with 405 forever.
        get_paths = {p for m, p in routes if m == "GET"}

        # -- SC701 / SC702: templated flags vs the argparse surface ------
        templated = _collect_template_flags(tmpl_text)
        for flag, line, vpath in templated:
            if flags and flag not in flags:
                if not _yaml_allowed(tmpl_lines, line, "SC701"):
                    out.append(Violation(
                        rule="SC701", file=surface.template, line=line,
                        qualname=surface.values_spec or surface.template,
                        message=(
                            f"template passes `{flag}` but "
                            f"{surface.argparse_file} declares no such "
                            "flag — the pod would crash-loop on argparse "
                            "error"
                        ),
                        detail=flag,
                    ))
                continue
            if vpath is None or flag not in flags:
                continue
            chart_default = _normalize_default(
                miniyaml.get_path(values, vpath)
            )
            arg_default = _normalize_default(flags[flag].get("default"))
            if chart_default is None or arg_default is None:
                continue
            if chart_default != arg_default:
                key_line = value_key_lines.get(vpath, 1)
                if _yaml_allowed(values_lines, key_line, "SC702"):
                    continue
                out.append(Violation(
                    rule="SC702", file=cfg.helm_values_path or "values.yaml",
                    line=key_line,
                    qualname=vpath,
                    message=(
                        f"values default `{vpath}: {chart_default}` is "
                        f"templated into `{flag}` whose argparse default "
                        f"is `{arg_default}` — chart-default deployments "
                        "silently diverge from the binary default; align "
                        "them or annotate the values key with the reason"
                    ),
                    detail=f"{vpath}!={flag}",
                ))

        # -- SC703: probes and preStop hooks vs server routes -------------
        if routes:
            probe_paths: List[Tuple[str, str, int]] = []  # (path, file, line)
            for i, line in enumerate(tmpl_lines):
                pm = _HTTP_PATH_RE.match(line)
                if pm is not None:
                    probe_paths.append((pm.group(1), surface.template, i + 1))
            if surface.values_spec:
                for probe_key in (
                    "startupProbe", "livenessProbe", "readinessProbe"
                ):
                    vpath = f"{surface.values_spec}.{probe_key}.httpGet.path"
                    p = miniyaml.get_path(values, vpath)
                    if isinstance(p, str):
                        probe_paths.append((
                            p, cfg.helm_values_path or "values.yaml",
                            value_key_lines.get(vpath, 1),
                        ))
            for p, file, line in probe_paths:
                if p not in get_paths:
                    src_lines = (
                        values_lines if file == cfg.helm_values_path
                        else tmpl_lines
                    )
                    if _yaml_allowed(src_lines, line, "SC703"):
                        continue
                    out.append(Violation(
                        rule="SC703", file=file, line=line,
                        qualname=surface.values_spec or surface.template,
                        message=(
                            f"probe path `{p}` is not a registered GET "
                            f"route on the target server "
                            f"({', '.join(surface.route_files)}) — the "
                            "kubelet's GET probe would never pass"
                        ),
                        detail=p,
                    ))
            for m in _PRESTOP_PATH_RE.finditer(tmpl_text):
                p = m.group(1)
                line = tmpl_text[:m.start()].count("\n") + 1
                if ("POST", p) not in routes:
                    if _yaml_allowed(tmpl_lines, line, "SC703"):
                        continue
                    out.append(Violation(
                        rule="SC703", file=surface.template, line=line,
                        qualname=surface.values_spec or surface.template,
                        message=(
                            f"preStop hook POSTs `{p}` but the server "
                            "registers no POST route there — graceful "
                            "drain would silently no-op"
                        ),
                        detail=f"preStop:{p}",
                    ))
            # Probe port names must be declared container port names.
            declared_ports = set(_NAMED_PORT_RE.findall(tmpl_text))
            if surface.values_spec and declared_ports:
                for probe_key in (
                    "startupProbe", "livenessProbe", "readinessProbe"
                ):
                    vpath = f"{surface.values_spec}.{probe_key}.httpGet.port"
                    port = miniyaml.get_path(values, vpath)
                    if (
                        isinstance(port, str)
                        and port not in declared_ports
                    ):
                        if _yaml_allowed(
                            values_lines, value_key_lines.get(vpath, 1),
                            "SC703",
                        ):
                            continue
                        out.append(Violation(
                            rule="SC703",
                            file=cfg.helm_values_path or "values.yaml",
                            line=value_key_lines.get(vpath, 1),
                            qualname=vpath,
                            message=(
                                f"probe targets port name `{port}` but the "
                                "template declares no container port with "
                                f"that name (declared: {sorted(declared_ports)})"
                            ),
                            detail=port,
                        ))

        # -- SC704: drain-grace threading ---------------------------------
        if surface.drain_values_spec:
            spec = surface.drain_values_spec
            grace_ref = f"{spec}.drainGraceSeconds"
            flag_threaded = False
            for flag, line, vpath in templated:
                if flag == "--drain-grace-s" and vpath == grace_ref:
                    flag_threaded = True
            if not flag_threaded and not _yaml_allowed(
                tmpl_lines, 1, "SC704"
            ):
                out.append(Violation(
                    rule="SC704", file=surface.template, line=1,
                    qualname=spec,
                    message=(
                        f"template does not thread `{grace_ref}` into "
                        "`--drain-grace-s` — the chart knob would not "
                        "reach the binary"
                    ),
                    detail=f"{grace_ref}->--drain-grace-s",
                ))
            term_ref = f"{spec}.terminationGracePeriodSeconds"
            if not re.search(
                r"terminationGracePeriodSeconds:\s*\{\{[^}]*"
                + re.escape(term_ref), tmpl_text,
            ) and not _yaml_allowed(tmpl_lines, 1, "SC704"):
                out.append(Violation(
                    rule="SC704", file=surface.template, line=1,
                    qualname=spec,
                    message=(
                        "template does not source "
                        f"terminationGracePeriodSeconds from `{term_ref}` "
                        "— the SIGKILL deadline would not track the "
                        "drain grace"
                    ),
                    detail=f"{term_ref}->terminationGracePeriodSeconds",
                ))

        # -- SC705: template values refs vs the schema --------------------
        if schema is not None:
            seen: Set[str] = set()
            for ref, line in _collect_values_refs(tmpl_text):
                if ref in seen:
                    continue
                seen.add(ref)
                if not _schema_has(schema, ref):
                    if _yaml_allowed(tmpl_lines, line, "SC705"):
                        continue
                    out.append(Violation(
                        rule="SC705", file=surface.template, line=line,
                        qualname=ref,
                        message=(
                            f"template references `.Values.{ref}` but "
                            f"{cfg.helm_schema_path} does not declare it "
                            "— a typoed override would validate clean"
                        ),
                        detail=ref,
                    ))

    # -- SC704(c): termination > grace in every shipped values file --------
    # Strict excess, matching docs/robustness.md and the chart comments
    # ("must exceed"): the termination countdown also covers the preStop
    # hook and process teardown, so term == grace still SIGKILLs a drain
    # that uses its full budget.
    overlay_paths: List[
        Tuple[str, miniyaml.YamlValue, List[str], Dict[str, int]]
    ] = [
        (cfg.helm_values_path or "values.yaml", values, values_lines,
         value_key_lines)
    ]
    for rel in cfg.helm_overlay_paths:
        p = cfg.resolve(rel)
        if p is None or not p.exists():
            continue
        overlay_text = p.read_text()
        overlay, overlay_key_lines = miniyaml.parse(overlay_text)
        overlay_paths.append((
            rel, miniyaml.deep_merge(values, overlay),
            overlay_text.splitlines(), overlay_key_lines,
        ))
    # -- SC707: disagg role-pool contract ----------------------------------
    out.extend(_check_role_contract(
        cfg, values, values_lines, value_key_lines, overlay_paths
    ))
    # -- SC709: multi-host pod-group contract ------------------------------
    out.extend(_check_slice_contract(cfg, overlay_paths))

    drain_specs = sorted({
        s.drain_values_spec
        for s in cfg.deployment_surfaces
        if s.drain_values_spec
    })
    spec_prefixes = sorted(
        {s.values_spec for s in cfg.deployment_surfaces if s.values_spec}
        | {
            s.drain_values_spec
            for s in cfg.deployment_surfaces
            if s.drain_values_spec is not None
        }
    )
    for rel, merged, file_lines, file_key_lines in overlay_paths:
        for spec in drain_specs:
            grace = miniyaml.get_path(merged, f"{spec}.drainGraceSeconds")
            term = miniyaml.get_path(
                merged, f"{spec}.terminationGracePeriodSeconds"
            )
            if isinstance(grace, (int, float)) and isinstance(
                term, (int, float)
            ):
                if term <= grace:
                    line = file_key_lines.get(
                        f"{spec}.terminationGracePeriodSeconds",
                        file_key_lines.get(spec, 1),
                    )
                    if _yaml_allowed(file_lines, line, "SC704"):
                        continue
                    out.append(Violation(
                        rule="SC704", file=rel, line=line, qualname=spec,
                        message=(
                            f"{spec}.terminationGracePeriodSeconds "
                            f"({term}) <= drainGraceSeconds ({grace}): "
                            "the termination countdown also covers the "
                            "preStop hook and teardown, so the kubelet "
                            "SIGKILLs a pod that uses its full drain "
                            "budget — set it strictly greater"
                        ),
                        detail=f"{rel}:{spec}:termination<=grace",
                    ))

    # -- SC706: docs/robustness.md helm table vs values.yaml ---------------
    docs_path = cfg.resolve(cfg.robustness_docs_path)
    if docs_path is not None and docs_path.exists() and spec_prefixes:
        docs_text = docs_path.read_text()
        # `_yaml_allowed` works on any line-commented text; in markdown
        # the annotation rides an HTML comment on the table row, e.g.
        # `<!-- # stackcheck: allow=SC706 reason=... -->`.
        docs_lines = docs_text.splitlines()
        # Like SC704(c), the recognized spec subtrees come from the
        # configured deployment surfaces, not a hardcoded tuple — a new
        # surface's docs rows join the drift check automatically.
        row_re = re.compile(
            r"^\|\s*`((?:"
            + "|".join(re.escape(p) for p in spec_prefixes)
            + r")\.[A-Za-z0-9_.]+)`\s*\|\s*([^|]*)\|",
            re.M,
        )
        for m in row_re.finditer(docs_text):
            key, documented = m.group(1), m.group(2).strip().strip("`")
            line = docs_text[:m.start()].count("\n") + 1
            actual = miniyaml.get_path(values, key)
            if key not in value_key_lines:
                if _yaml_allowed(docs_lines, line, "SC706"):
                    continue
                out.append(Violation(
                    rule="SC706",
                    file=cfg.robustness_docs_path or "docs/robustness.md",
                    line=line, qualname=key,
                    message=(
                        f"docs table documents `{key}` but values.yaml "
                        "has no such key (renamed or removed?)"
                    ),
                    detail=key,
                ))
                continue
            doc_default = _normalize_default(documented)
            actual_default = _normalize_default(actual)
            if (
                doc_default is not None
                and actual_default is not None
                and re.fullmatch(r"[0-9.]+", documented.strip())
                and doc_default != actual_default
                and not _yaml_allowed(docs_lines, line, "SC706")
            ):
                out.append(Violation(
                    rule="SC706",
                    file=cfg.robustness_docs_path or "docs/robustness.md",
                    line=line, qualname=key,
                    message=(
                        f"docs table documents `{key}` default as "
                        f"`{documented.strip()}` but values.yaml says "
                        f"`{actual_default}`"
                    ),
                    detail=f"{key}:default",
                ))
    return out
