# Namespace for repo tooling (tools.stackcheck).  Not part of the
# installed package (pyproject packages.find only picks production_stack_tpu*).
