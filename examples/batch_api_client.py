"""OpenAI Batch API client example against the TPU router.

Uploads a JSONL batch input file, creates a batch, polls until it
completes, and downloads the per-line results.  (Reference counterpart:
examples/openai_api_client_batch.py — that one only creates the batch; the
reference's processor is a simulation stub, while this stack executes every
line through the real routing path.)

Run (router started with --enable-batch-api):

    python examples/batch_api_client.py --base-url http://localhost:8001 \
        --model fake/llama-3-8b
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys

import aiohttp


def build_batch_input(model: str, questions) -> bytes:
    """One OpenAI batch line per question (custom_id, method, url, body)."""
    lines = []
    for i, question in enumerate(questions):
        lines.append(json.dumps({
            "custom_id": f"req-{i}",
            "method": "POST",
            "url": "/v1/chat/completions",
            "body": {
                "model": model,
                "messages": [{"role": "user", "content": question}],
                "max_tokens": 64,
            },
        }))
    return ("\n".join(lines) + "\n").encode()


async def run_batch(base_url: str, model: str, questions,
                    poll_interval: float = 0.5, timeout: float = 120.0):
    async with aiohttp.ClientSession() as session:
        # 1. Upload the input file (multipart, purpose=batch).
        form = aiohttp.FormData()
        form.add_field("purpose", "batch")
        form.add_field("file", build_batch_input(model, questions),
                       filename="batch_input.jsonl",
                       content_type="application/jsonl")
        async with session.post(f"{base_url}/v1/files", data=form) as resp:
            resp.raise_for_status()
            input_file = await resp.json()
        print(f"uploaded input file: {input_file['id']}")

        # 2. Create the batch.
        async with session.post(f"{base_url}/v1/batches", json={
            "input_file_id": input_file["id"],
            "endpoint": "/v1/chat/completions",
            "completion_window": "24h",
        }) as resp:
            resp.raise_for_status()
            batch = await resp.json()
        print(f"created batch: {batch['id']} (status {batch['status']})")

        # 3. Poll until done.
        deadline = asyncio.get_event_loop().time() + timeout
        while batch["status"] not in ("completed", "failed", "expired",
                                      "cancelled"):
            if asyncio.get_event_loop().time() > deadline:
                raise TimeoutError(f"batch stuck in {batch['status']}")
            await asyncio.sleep(poll_interval)
            async with session.get(f"{base_url}/v1/batches/{batch['id']}") as resp:
                resp.raise_for_status()
                batch = await resp.json()
        print(f"batch finished: {batch['status']} "
              f"(completed={batch['request_counts']['completed']} "
              f"failed={batch['request_counts']['failed']})")

        # 4. Download results.
        results = []
        if batch.get("output_file_id"):
            async with session.get(
                f"{base_url}/v1/files/{batch['output_file_id']}/content"
            ) as resp:
                resp.raise_for_status()
                text = await resp.text()
            for line in text.splitlines():
                results.append(json.loads(line))
        return batch, results


def main(argv=None) -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--base-url", default="http://localhost:8001")
    parser.add_argument("--model", required=True)
    args = parser.parse_args(argv)

    questions = [
        "What is a TPU systolic array?",
        "Explain paged attention in one sentence.",
        "Why is decode bandwidth-bound?",
    ]
    batch, results = asyncio.run(run_batch(args.base_url, args.model, questions))
    for row in results:
        body = row.get("response", {}).get("body", {})
        content = (body.get("choices") or [{}])[0].get("message", {}).get("content")
        print(f"{row['custom_id']}: {content!r}")
    return 0 if batch["status"] == "completed" else 1


if __name__ == "__main__":
    sys.exit(main())
