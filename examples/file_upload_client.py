"""OpenAI Files API client example against the TPU router.

Upload, inspect, list, download, and delete a file.  (Reference
counterpart: src/examples/example_file_upload.py.)

Run (router started with --enable-batch-api):

    python examples/file_upload_client.py --base-url http://localhost:8001
"""

from __future__ import annotations

import argparse
import asyncio
import sys

import aiohttp


async def file_roundtrip(base_url: str, content: bytes,
                         filename: str = "example.jsonl") -> dict:
    async with aiohttp.ClientSession() as session:
        form = aiohttp.FormData()
        form.add_field("purpose", "batch")
        form.add_field("file", content, filename=filename,
                       content_type="application/jsonl")
        async with session.post(f"{base_url}/v1/files", data=form) as resp:
            resp.raise_for_status()
            created = await resp.json()
        print(f"uploaded: {created['id']} ({created['bytes']} bytes)")

        async with session.get(f"{base_url}/v1/files/{created['id']}") as resp:
            meta = await resp.json()
        print(f"metadata: filename={meta['filename']} purpose={meta['purpose']}")

        async with session.get(f"{base_url}/v1/files") as resp:
            listing = await resp.json()
        print(f"listed {len(listing['data'])} file(s)")

        async with session.get(
            f"{base_url}/v1/files/{created['id']}/content"
        ) as resp:
            downloaded = await resp.read()
        assert downloaded == content, "round-trip mismatch"
        print("content round-trips byte-exact")

        async with session.delete(f"{base_url}/v1/files/{created['id']}") as resp:
            deleted = await resp.json()
        print(f"deleted: {deleted['deleted']}")
        return created


def main(argv=None) -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--base-url", default="http://localhost:8001")
    args = parser.parse_args(argv)
    asyncio.run(file_roundtrip(
        args.base_url, b'{"example": 1}\n{"example": 2}\n'
    ))
    return 0


if __name__ == "__main__":
    sys.exit(main())
